"""Recurrent layers.

Parity: python/paddle/fluid/layers/nn.py dynamic_lstm/dynamic_gru/
lstm/gru_unit/lstm_unit and the cuDNN-backed fluid.layers.lstm.

Ragged inputs follow the paddle_tpu LoD convention (SURVEY.md §1 decision 4):
``(batch, max_len, ...)`` padded data + an optional int32 ``length`` tensor
instead of LoD offsets; kernels are one lax.scan with hoisted input
projections (ops/rnn_ops.py).
"""

import copy

from ..core.layer_helper import LayerHelper

__all__ = ["dynamic_lstm", "dynamic_gru", "lstm", "gru", "lstm_unit",
           "gru_unit"]


def _suffixed(attr, nm):
    """A named ParamAttr must not collapse WeightX/WeightH onto one name."""
    if attr is False or attr is None or getattr(attr, "name", None) is None:
        return attr
    a = copy.copy(attr)
    a.name = f"{attr.name}_{nm}"
    return a


def dynamic_lstm(input, size, length=None, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None, use_peepholes=True,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 dtype="float32", name=None):
    """Parity: fluid.layers.dynamic_lstm (ref layers/nn.py:dynamic_lstm).

    input: (B, T, D). size = 4 * hidden (fluid convention). Unlike the
    reference (which takes pre-projected input from an fc), this takes raw
    input and owns BOTH weights: WeightX (D, 4H) and WeightH (H, 4H) — the
    input projection is hoisted out of the scan onto one big MXU matmul.
    Returns (hidden (B, T, H), cell (B, T, H)).
    """
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    d = input.shape[-1]
    w_x = helper.create_parameter(_suffixed(helper.param_attr, "wx"),
                                  [d, 4 * hidden], dtype)
    w_h = helper.create_parameter(_suffixed(helper.param_attr, "wh"),
                                  [hidden, 4 * hidden], dtype)
    bias_len = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(helper.bias_attr, [bias_len], dtype,
                                   is_bias=True)
    hs = helper.create_variable_for_type_inference(
        dtype, tuple(input.shape[:2]) + (hidden,))
    cs = helper.create_variable_for_type_inference(
        dtype, tuple(input.shape[:2]) + (hidden,))
    h_last = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], hidden))
    c_last = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], hidden))
    inputs = {"Input": input, "WeightX": w_x, "WeightH": w_h, "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("lstm", inputs,
                     {"Hidden": hs, "Cell": cs, "LastH": h_last,
                      "LastC": c_last},
                     {"use_peepholes": use_peepholes,
                      "is_reverse": is_reverse})
    return hs, cs


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, length=None, dropout_prob=0.0, is_bidirec=False,
         is_test=False, param_attr=None, bias_attr=None, dtype="float32",
         name=None):
    """Parity: fluid.layers.lstm (the cuDNN multi-layer LSTM). Stacked
    (optionally bidirectional) scan LSTMs. Returns (out, last_h, last_c)
    with out (B, T, H * num_directions)."""
    from . import nn as nn_layers
    from .sequence import sequence_last_step, sequence_first_step
    x = input
    for layer in range(num_layers):
        lp = _suffixed(param_attr, f"l{layer}")
        lb = _suffixed(bias_attr, f"l{layer}")
        fwd, fwd_c = dynamic_lstm(x, 4 * hidden_size, length=length,
                                  use_peepholes=False, param_attr=lp,
                                  bias_attr=lb, dtype=dtype)
        if is_bidirec:
            bwd, bwd_c = dynamic_lstm(x, 4 * hidden_size, length=length,
                                      use_peepholes=False, is_reverse=True,
                                      param_attr=_suffixed(lp, "rev"),
                                      bias_attr=_suffixed(lb, "rev"),
                                      dtype=dtype)
            x = nn_layers.concat([fwd, bwd], axis=-1)
        else:
            x = fwd
        if dropout_prob and not is_test and layer < num_layers - 1:
            x = nn_layers.dropout(x, dropout_prob)
    # Final states from the LAST layer. A reversed LSTM's sequence output is
    # stored in input order, so its final state sits at t=0.
    if is_bidirec:
        last_h = nn_layers.concat(
            [sequence_last_step(fwd, length=length),
             sequence_first_step(bwd, length=length)], axis=-1)
        last_c = nn_layers.concat(
            [sequence_last_step(fwd_c, length=length),
             sequence_first_step(bwd_c, length=length)], axis=-1)
    else:
        last_h = sequence_last_step(fwd, length=length)
        last_c = sequence_last_step(fwd_c, length=length)
    return x, last_h, last_c


def dynamic_gru(input, size, length=None, h_0=None, param_attr=None,
                bias_attr=None, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", origin_mode=False,
                dtype="float32", name=None):
    """Parity: fluid.layers.dynamic_gru. input (B, T, D); size = hidden.
    Owns WeightX (D, 3H) + WeightH (H, 3H); returns hidden (B, T, H)."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = input.shape[-1]
    w_x = helper.create_parameter(_suffixed(helper.param_attr, "wx"),
                                  [d, 3 * size], dtype)
    w_h = helper.create_parameter(_suffixed(helper.param_attr, "wh"),
                                  [size, 3 * size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [3 * size], dtype,
                                   is_bias=True)
    hs = helper.create_variable_for_type_inference(
        dtype, tuple(input.shape[:2]) + (size,))
    h_last = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], size))
    inputs = {"Input": input, "WeightX": w_x, "WeightH": w_h, "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    if h_0 is not None:
        inputs["H0"] = h_0
    helper.append_op("gru", inputs, {"Hidden": hs, "LastH": h_last},
                     {"is_reverse": is_reverse,
                      "origin_mode": origin_mode})
    return hs


gru = dynamic_gru


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Parity: fluid.layers.lstm_unit — one step; projects [x, h_prev] to
    4H gates with an fc then applies the cell. Returns (hidden, cell)."""
    from . import nn as nn_layers
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = cell_t_prev.shape[-1]
    concat = nn_layers.concat([x_t, hidden_t_prev], axis=-1)
    gates = nn_layers.fc(concat, 4 * size, param_attr=param_attr,
                         bias_attr=bias_attr)
    h = helper.create_variable_for_type_inference(x_t.dtype,
                                                  (x_t.shape[0], size))
    c = helper.create_variable_for_type_inference(x_t.dtype,
                                                  (x_t.shape[0], size))
    helper.append_op("lstm_unit", {"X": gates, "C_prev": cell_t_prev},
                     {"Hidden": h, "Cell": c},
                     {"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False, name=None):
    """Parity: fluid.layers.gru_unit — one step. input (B, 3H) is the
    pre-projected x (fluid convention: caller fc's x to 3H); size = 3 * H.
    Returns (hidden, reset_hidden_prev, gate).

    Documented divergence: the reference ACCEPTS origin_mode here but
    silently drops it (only dynamic_gru forwards it, nn.py:1260); we
    honor the flag — passing True gives the original-paper blend
    instead of reproducing the reference's silent-drop quirk."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    h_size = size // 3
    w = helper.create_parameter(helper.param_attr, [h_size, 3 * h_size],
                                input.dtype)
    bias = helper.create_parameter(helper.bias_attr, [3 * h_size],
                                   input.dtype, is_bias=True)
    h = helper.create_variable_for_type_inference(input.dtype,
                                                  (input.shape[0], h_size))
    gate = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], 3 * h_size))
    rhp = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], h_size))
    inputs = {"Input": input, "HiddenPrev": hidden, "Weight": w}
    if bias is not None:
        inputs["Bias"] = bias
    helper.append_op("gru_unit", inputs,
                     {"Hidden": h, "Gate": gate, "ResetHiddenPrev": rhp},
                     {"origin_mode": origin_mode})
    return h, rhp, gate


def dynamic_lstmp(input, size, proj_size, length=None, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    """Parity: fluid.layers.dynamic_lstmp — LSTM with recurrent
    projection. size = 4 * hidden. Returns (projection (B, T, P),
    cell (B, T, H))."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    d = input.shape[-1]
    w_x = helper.create_parameter(_suffixed(helper.param_attr, "wx"),
                                  [d, 4 * hidden], dtype)
    w_h = helper.create_parameter(_suffixed(helper.param_attr, "wh"),
                                  [proj_size, 4 * hidden], dtype)
    w_p = helper.create_parameter(_suffixed(helper.param_attr, "wp"),
                                  [hidden, proj_size], dtype)
    bias_len = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(helper.bias_attr, [bias_len], dtype,
                                   is_bias=True)
    proj = helper.create_variable_for_type_inference(
        dtype, tuple(input.shape[:2]) + (proj_size,))
    cell = helper.create_variable_for_type_inference(
        dtype, tuple(input.shape[:2]) + (hidden,))
    r_last = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], proj_size))
    c_last = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], hidden))
    inputs = {"Input": input, "WeightX": w_x, "WeightH": w_h,
              "ProjWeight": w_p, "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("lstmp", inputs,
                     {"Projection": proj, "Cell": cell, "LastH": r_last,
                      "LastC": c_last},
                     {"use_peepholes": use_peepholes,
                      "is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation,
                      "proj_activation": proj_activation})
    return proj, cell
