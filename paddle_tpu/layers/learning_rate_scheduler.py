"""Learning-rate schedulers (graph-side).

Parity: python/paddle/fluid/layers/learning_rate_scheduler.py. Each scheduler
builds ops on a persistable global step counter; the LR is recomputed inside
the same jitted training step, so schedules are free (fused scalar math).
"""

import math

from ..core.framework import default_main_program
from ..core.layer_helper import LayerHelper
from . import tensor
from . import nn
from . import ops as ops_layers


LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin=0):
    """Float mirror of the shared @LR_DECAY_COUNTER@ (reference
    learning_rate_scheduler.py:45 — delegate to autoincreased_step_counter
    so the init-to-begin-minus-step rule lives in ONE place; the first
    executed step observes `begin`).

    NOTE reference-parity caveat: the counter is shared per program, so
    `begin` only takes effect for the FIRST scheduler built — mixing
    noam_decay (begin=1) with another schedule (begin=0) in one program
    follows whichever was built first, exactly like fluid."""
    from .control_flow import autoincreased_step_counter

    helper = LayerHelper("global_step_counter")
    gb = default_main_program().global_block()
    if LR_COUNTER_NAME in gb.vars:
        return gb.vars[LR_COUNTER_NAME + ".float"]
    counter = autoincreased_step_counter(LR_COUNTER_NAME, begin=begin,
                                         step=1)
    fcounter = helper.create_or_get_global_variable(
        LR_COUNTER_NAME + ".float", shape=(), dtype="float32")
    helper.append_op("cast", {"X": counter}, {"Out": fcounter},
                     {"out_dtype": "float32"})
    fcounter.stop_gradient = True
    return fcounter


def noam_decay(d_model, warmup_steps):
    step = _decay_step_counter(1)
    a = nn.elementwise_pow(step, tensor.fill_constant((), "float32", -0.5))
    b = nn.elementwise_mul(step, tensor.fill_constant(
        (), "float32", warmup_steps ** -1.5))
    lr = nn.elementwise_min(a, b)
    return nn.scale(lr, scale=d_model ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops_layers.floor(div)
    factor = nn.elementwise_pow(
        tensor.fill_constant((), "float32", decay_rate), div)
    return nn.scale(factor, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops_layers.floor(div)
    return nn.scale(ops_layers.exp(nn.scale(div, scale=-decay_rate)),
                    scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops_layers.floor(div)
    denom = nn.scale(div, scale=decay_rate, bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant((), "float32", float(learning_rate)), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div = nn.elementwise_max(
            ops_layers.ceil(nn.scale(step, scale=1.0 / decay_steps)),
            tensor.fill_constant((), "float32", 1.0))
        decay_steps_var = nn.scale(div, scale=float(decay_steps))
        frac = nn.elementwise_div(step, decay_steps_var)
    else:
        capped = nn.elementwise_min(
            step, tensor.fill_constant((), "float32", float(decay_steps)))
        frac = nn.scale(capped, scale=1.0 / decay_steps)
    base = nn.elementwise_pow(
        nn.scale(frac, scale=-1.0, bias=1.0),
        tensor.fill_constant((), "float32", power))
    return nn.scale(base, scale=float(learning_rate) - end_learning_rate,
                    bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    step = _decay_step_counter()
    lr = tensor.fill_constant((), "float32", values[-1])
    # evaluate from the last boundary back so earlier ranges win
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = nn.cast(
            nn._make_logical("less_than")(
                step, tensor.fill_constant((), "float32", float(b))),
            "float32")
        lr = nn.elementwise_add(
            nn.elementwise_mul(cond, tensor.fill_constant((), "float32", v)),
            nn.elementwise_mul(nn.scale(cond, scale=-1.0, bias=1.0), lr))
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = ops_layers.floor(nn.scale(step, scale=1.0 / step_each_epoch))
    cos_arg = nn.scale(epoch, scale=math.pi / epochs)
    decayed = nn.scale(ops_layers.cos(cos_arg), scale=0.5, bias=0.5,
                       bias_after_scale=True)
    return nn.scale(decayed, scale=float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    if not hasattr(learning_rate, "name"):
        learning_rate = tensor.fill_constant((), "float32", float(learning_rate))
    warm = nn.scale(step, scale=(end_lr - start_lr) / warmup_steps,
                    bias=start_lr)
    in_warmup = nn.cast(
        nn._make_logical("less_than")(
            step, tensor.fill_constant((), "float32", float(warmup_steps))),
        "float32")
    return nn.elementwise_add(
        nn.elementwise_mul(in_warmup, warm),
        nn.elementwise_mul(nn.scale(in_warmup, scale=-1.0, bias=1.0),
                           learning_rate))
