"""Monkey-patch arithmetic operators onto Variable.

Parity: python/paddle/fluid/layers/math_op_patch.py.
"""

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from . import tensor as tensor_layers


def _to_var(other, ref):
    if isinstance(other, Variable):
        return other
    return tensor_layers.fill_constant(ref.shape if ref.shape else (),
                                       ref.dtype, float(other))


def _binary(op_type, reverse=False):
    def impl(self, other):
        if not isinstance(other, Variable) and op_type in (
                "elementwise_add", "elementwise_sub", "elementwise_mul",
                "elementwise_div"):
            # scalar fast path via scale op
            helper = LayerHelper("scale")
            out = helper.create_variable_for_type_inference(self.dtype, self.shape)
            v = float(other)
            if op_type == "elementwise_add":
                helper.append_op("scale", {"X": self}, {"Out": out},
                                 {"scale": 1.0, "bias": v})
            elif op_type == "elementwise_sub":
                if reverse:
                    helper.append_op("scale", {"X": self}, {"Out": out},
                                     {"scale": -1.0, "bias": v})
                else:
                    helper.append_op("scale", {"X": self}, {"Out": out},
                                     {"scale": 1.0, "bias": -v})
            elif op_type == "elementwise_mul":
                helper.append_op("scale", {"X": self}, {"Out": out},
                                 {"scale": v, "bias": 0.0})
            else:
                if reverse:
                    other_var = _to_var(other, self)
                    return _append(op_type, other_var, self)
                helper.append_op("scale", {"X": self}, {"Out": out},
                                 {"scale": 1.0 / v, "bias": 0.0})
            return out
        other_var = _to_var(other, self)
        if reverse:
            return _append(op_type, other_var, self)
        return _append(op_type, self, other_var)
    return impl


def _append(op_type, x, y):
    helper = LayerHelper(op_type)
    dtype = x.dtype
    if op_type in ("less_than", "less_equal", "greater_than", "greater_equal",
                   "equal", "not_equal"):
        dtype = "bool"
    shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
    out = helper.create_variable_for_type_inference(dtype, shape)
    attrs = {"axis": -1} if op_type.startswith("elementwise") else {}
    helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, attrs)
    return out


def _neg(self):
    helper = LayerHelper("scale")
    out = helper.create_variable_for_type_inference(self.dtype, self.shape)
    helper.append_op("scale", {"X": self}, {"Out": out},
                     {"scale": -1.0, "bias": 0.0})
    return out


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add")
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul")
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__mod__ = _binary("elementwise_mod")
    Variable.__floordiv__ = _binary("elementwise_floordiv")
    Variable.__lt__ = _binary("less_than")
    Variable.__le__ = _binary("less_equal")
    Variable.__gt__ = _binary("greater_than")
    Variable.__ge__ = _binary("greater_equal")
    Variable.__neg__ = _neg
