"""Beam-search layers.

Parity: fluid.layers.beam_search / beam_search_decode
(python/paddle/fluid/layers/nn.py + operators/beam_search_op.cc).
The reference runs beam search as LoD surgery inside a While block with
host-visible pruning. TPU-native: dense (batch, beam) lanes, finished beams
masked, whole decode as lax.scan — see ops/beam_search_ops.py and the
functional `beam_search_decode_loop` used by models/transformer.py.
"""

from ..core.layer_helper import LayerHelper

__all__ = ["beam_search", "beam_search_decode"]


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One expansion step (reference arg ORDER: name before
    return_parent_idx, nn.py:4555). scores: (B*K, V) probabilities.
    Returns (selected_ids (B*K, 1), selected_scores (B*K, 1)) — plus
    parent_idx (B*K,) when return_parent_idx=True."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference(
        "int64", (scores.shape[0], 1))
    sel_scores = helper.create_variable_for_type_inference(
        scores.dtype, (scores.shape[0], 1))
    parent = helper.create_variable_for_type_inference(
        "int32", (scores.shape[0],))
    helper.append_op(
        "beam_search",
        {"PreIds": pre_ids, "PreScores": pre_scores, "Ids": ids,
         "Scores": scores},
        {"SelectedIds": sel_ids, "SelectedScores": sel_scores,
         "ParentIdx": parent},
        {"beam_size": beam_size, "end_id": end_id})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, parent_idx, scores, beam_size, end_id, name=None):
    """Backtrack stacked step outputs. ids/parent_idx: (T, B, K);
    scores: (B, K). Returns (sentence_ids (B, K, T), sentence_scores)."""
    helper = LayerHelper("beam_search_decode", name=name)
    t, b, k = ids.shape
    sent_ids = helper.create_variable_for_type_inference("int64", (b, k, t))
    sent_scores = helper.create_variable_for_type_inference(
        scores.dtype, (b, k))
    helper.append_op(
        "beam_search_decode",
        {"Ids": ids, "ParentIdx": parent_idx, "Scores": scores},
        {"SentenceIds": sent_ids, "SentenceScores": sent_scores},
        {"beam_size": beam_size, "end_id": end_id})
    return sent_ids, sent_scores
