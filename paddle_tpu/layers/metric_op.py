"""Metric layers.

Parity: python/paddle/fluid/layers/metric_op.py (accuracy, auc).
"""

from ..core.layer_helper import LayerHelper
from .. import initializer as init_mod


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_idx = helper.create_variable_for_type_inference("int64")
    helper.append_op("top_k", {"X": input},
                     {"Out": topk_out, "Indices": topk_idx}, {"k": k})
    acc = helper.create_variable_for_type_inference("float32", (1,))
    correct = correct or helper.create_variable_for_type_inference("int32", (1,))
    total = total or helper.create_variable_for_type_inference("int32", (1,))
    helper.append_op("accuracy",
                     {"Out": topk_out, "Indices": topk_idx, "Label": label},
                     {"Accuracy": acc, "Correct": correct, "Total": total})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    if str(curve).upper() != "ROC":
        raise NotImplementedError(
            "auc: only curve='ROC' is implemented (the exact rank-"
            "statistic form); the reference's 'PR' curve is not ported")
    helper = LayerHelper("auc")
    n = num_thresholds + 1
    stat_pos = helper.create_or_get_global_variable(
        helper.name + ".stat_pos", shape=(n,), dtype="float32", persistable=True)
    stat_neg = helper.create_or_get_global_variable(
        helper.name + ".stat_neg", shape=(n,), dtype="float32", persistable=True)
    stat_pos.stop_gradient = True
    stat_neg.stop_gradient = True
    init_mod.ConstantInitializer(0.0)(stat_pos)
    init_mod.ConstantInitializer(0.0)(stat_neg)
    auc_out = helper.create_variable_for_type_inference("float32", (1,))
    helper.append_op("auc",
                     {"Predict": input, "Label": label, "StatPos": stat_pos,
                      "StatNeg": stat_neg},
                     {"AUC": auc_out, "StatPosOut": stat_pos,
                      "StatNegOut": stat_neg},
                     {"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, auc_out, [stat_pos, stat_neg]


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32", (1,))
    wrong = helper.create_variable_for_type_inference("float32", (num_classes,))
    correct = helper.create_variable_for_type_inference("float32", (num_classes,))
    helper.append_op("mean_iou", {"Predictions": input, "Labels": label},
                     {"OutMeanIou": miou, "OutWrong": wrong,
                      "OutCorrect": correct},
                     {"num_classes": num_classes})
    return miou, wrong, correct


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Parity: fluid.layers.edit_distance (Levenshtein on padded seqs);
    ignored_tokens erase from both sides first (ref nn.py:5671-5689,
    the sequence_erase op)."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens:
        def _erase(seq, length):
            erased = helper.create_variable_for_type_inference(seq.dtype,
                                                               seq.shape)
            new_len = helper.create_variable_for_type_inference("int32")
            ins_e = {"X": seq}
            if length is not None:
                ins_e["Length"] = length
            helper.append_op("sequence_erase", ins_e,
                             {"Out": erased, "Length": new_len},
                             {"tokens": list(ignored_tokens)})
            return erased, new_len
        input, input_length = _erase(input, input_length)
        label, label_length = _erase(label, label_length)
    out = helper.create_variable_for_type_inference("float32",
                                                    (input.shape[0], 1))
    seq_num = helper.create_variable_for_type_inference("int32", (1,))
    ins = {"Hyps": input, "Refs": label}
    if input_length is not None:
        ins["HypsLength"] = input_length
    if label_length is not None:
        ins["RefsLength"] = label_length
    helper.append_op("edit_distance", ins,
                     {"Out": out, "SequenceNum": seq_num},
                     {"normalized": normalized})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Parity: fluid.layers.chunk_eval (IOB span P/R/F1)."""
    helper = LayerHelper("chunk_eval")
    mk = lambda d, s: helper.create_variable_for_type_inference(d, s)
    precision, recall, f1 = mk("float32", (1,)), mk("float32", (1,)), mk("float32", (1,))
    n_infer, n_label, n_correct = mk("int64", (1,)), mk("int64", (1,)), mk("int64", (1,))
    ins = {"Inference": input, "Label": label}
    if seq_length is not None:
        ins["SeqLength"] = seq_length
    helper.append_op("chunk_eval", ins,
                     {"Precision": precision, "Recall": recall,
                      "F1-Score": f1, "NumInferChunks": n_infer,
                      "NumLabelChunks": n_label,
                      "NumCorrectChunks": n_correct},
                     {"chunk_scheme": chunk_scheme,
                      "num_chunk_types": num_chunk_types})
    return precision, recall, f1, n_infer, n_label, n_correct
