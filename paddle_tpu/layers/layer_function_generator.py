"""Layer-function codegen helpers.

Parity: python/paddle/fluid/layers/layer_function_generator.py
(``generate_layer_fn``, ``generate_activation_fn``, ``deprecated``,
``autodoc``, ``templatedoc``). The reference generates python layer
functions from C++ OpProtos; here they generate from the op registry —
same calling convention for the simple one-in/one-out ops they cover
(everything richer has a hand-written layer).
"""

import functools
import re
import warnings

from ..core.layer_helper import LayerHelper

__all__ = ["deprecated", "generate_layer_fn", "generate_activation_fn",
           "autodoc", "templatedoc"]


def _register_exists(op_type):
    from .. import ops as ops_registry
    return op_type in ops_registry._REGISTRY


def generate_layer_fn(op_type):
    """A layer fn for a registered one-X-in / one-Out op: positional or
    x= input, remaining kwargs become op attrs (ref :119)."""
    if not _register_exists(op_type):
        raise ValueError(f"no registered op {op_type!r}")

    def layer_fn(x=None, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(
            getattr(x, "dtype", "float32"),
            getattr(x, "shape", None))
        ins = {"X": x} if x is not None else {}
        helper.append_op(op_type, ins, {"Out": out}, attrs)
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = f"Generated layer for the `{op_type}` op."
    return layer_fn


def generate_activation_fn(op_type):
    """A layer fn for a registered elementwise activation (ref :255)."""
    fn = generate_layer_fn(op_type)
    fn.__doc__ = f"Generated activation layer for `{op_type}`: " \
                 f"Out = {op_type}(X)."
    return fn


def deprecated(func_or_class):
    """Mark an API deprecated: wraps it with a DeprecationWarning
    (ref :42)."""

    @functools.wraps(func_or_class)
    def wrapped(*args, **kwargs):
        warnings.warn(f"{func_or_class.__name__} is deprecated",
                      DeprecationWarning, stacklevel=2)
        return func_or_class(*args, **kwargs)

    return wrapped


def autodoc(comment=""):
    """Prepend a comment to the function's docstring (ref :378)."""

    def deco(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func

    return deco


_TEMPLATE = re.compile(r"\$\{([a-z0-9_]+)(_comment|_type)?\}")


def templatedoc(op_type=None):
    """Substitute ``${x_comment}``-style placeholders in the docstring
    with the op name (ref :400; the reference pulls OpProto comments —
    here the op type itself, which keeps docs renderable)."""

    def deco(func):
        nm = op_type or func.__name__
        if func.__doc__:
            func.__doc__ = _TEMPLATE.sub(
                lambda m: m.group(1) if m.group(2) else nm, func.__doc__)
        return func

    return deco
