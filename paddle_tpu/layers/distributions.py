"""Probability distributions.

Parity: python/paddle/fluid/layers/distributions.py (Uniform:113, Normal:246,
Categorical:401, MultivariateNormalDiag:494) — same math, same API
(sample/entropy/log_prob/kl_divergence), built from paddle_tpu layers so the
graphs work in both static programs and dygraph. TPU notes: samples come from
the framework's seeded RNG ops (static shapes; no host sync), and the
clamped-uniform log_prob keeps the reference's log(0) = -inf behavior for
out-of-support values.
"""

import math

import numpy as np

from ..core.framework import Variable

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _to_var(value, dtype="float32"):
    from . import tensor as tensor_layers
    if isinstance(value, Variable):
        return value
    arr = np.asarray(value, dtype=dtype)
    if arr.ndim == 0:
        arr = arr.reshape(1)
    return tensor_layers.assign(arr)


class Distribution:
    """Abstract base (parity: layers/distributions.py:28)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    @staticmethod
    def _is_float_like(*args):
        return all(isinstance(a, (float, int)) for a in args)


class Uniform(Distribution):
    """U(low, high); see reference layers/distributions.py:113."""

    def __init__(self, low, high):
        self.all_arg_is_float = self._is_float_like(low, high)
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        from . import nn as nn_layers, ops as ops_layers
        batch_shape = list((self.low + self.high).shape)
        output_shape = list(shape) + batch_shape
        u = ops_layers.uniform_random(output_shape, min=0.0, max=1.0,
                                      seed=seed)
        out = u * (self.high - self.low) + self.low
        if self.all_arg_is_float:
            return nn_layers.reshape(out, list(shape))
        return out

    def log_prob(self, value):
        from . import nn as nn_layers, ops as ops_layers
        from . import control_flow
        lb = nn_layers.cast(control_flow.less_than(self.low, value),
                            value.dtype)
        ub = nn_layers.cast(control_flow.less_than(value, self.high),
                            value.dtype)
        return ops_layers.log(lb * ub) - ops_layers.log(self.high - self.low)

    def entropy(self):
        from . import ops as ops_layers
        return ops_layers.log(self.high - self.low)


class Normal(Distribution):
    """N(loc, scale); see reference layers/distributions.py:246."""

    def __init__(self, loc, scale):
        self.all_arg_is_float = self._is_float_like(loc, scale)
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        from . import nn as nn_layers, ops as ops_layers
        batch_shape = list((self.loc + self.scale).shape)
        output_shape = list(shape) + batch_shape
        z = ops_layers.gaussian_random(output_shape, mean=0.0, std=1.0,
                                       seed=seed)
        out = z * self.scale + self.loc
        if self.all_arg_is_float:
            return nn_layers.reshape(out, list(shape))
        return out

    def entropy(self):
        from . import ops as ops_layers
        return 0.5 + 0.5 * math.log(2 * math.pi) + ops_layers.log(self.scale)

    def log_prob(self, value):
        from . import ops as ops_layers
        var = self.scale * self.scale
        log_scale = ops_layers.log(self.scale)
        return (value - self.loc) * (value - self.loc) / (var * -2.0) \
            - log_scale - math.log(math.sqrt(2.0 * math.pi))

    def kl_divergence(self, other):
        from . import ops as ops_layers
        assert isinstance(other, Normal), \
            "another distribution must be Normal"
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return (var_ratio + t1 - 1.0 - ops_layers.log(var_ratio)) * 0.5


class Categorical(Distribution):
    """Categorical over unnormalized logits; reference
    layers/distributions.py:401 (fluid 1.5 exposes entropy + kl only)."""

    def __init__(self, logits):
        self.logits = _to_var(logits)

    def _norm(self, logits):
        from . import nn as nn_layers, ops as ops_layers
        shifted = logits - nn_layers.reduce_max(logits, dim=-1, keep_dim=True)
        e = ops_layers.exp(shifted)
        z = nn_layers.reduce_sum(e, dim=-1, keep_dim=True)
        return shifted, e, z

    def entropy(self):
        from . import nn as nn_layers, ops as ops_layers
        logits, e, z = self._norm(self.logits)
        prob = e / z
        return nn_layers.reduce_sum(prob * (logits - ops_layers.log(z)),
                                    dim=-1, keep_dim=True) * -1.0

    def kl_divergence(self, other):
        from . import nn as nn_layers, ops as ops_layers
        assert isinstance(other, Categorical)
        logits, e, z = self._norm(self.logits)
        o_logits, o_e, o_z = other._norm(other.logits)
        prob = e / z
        return nn_layers.reduce_sum(
            prob * (logits - ops_layers.log(z) - o_logits
                    + ops_layers.log(o_z)), dim=-1, keep_dim=True)

    def sample(self, shape, seed=0):
        """TPU extension (the reference left Categorical.sample
        unimplemented): Gumbel-max over the last axis."""
        from . import nn as nn_layers, ops as ops_layers
        logits = self.logits
        out_shape = list(shape) + list(logits.shape[:-1]) + [logits.shape[-1]]
        u = ops_layers.uniform_random(out_shape, min=1e-6, max=1.0 - 1e-6,
                                      seed=seed)
        g = ops_layers.log(ops_layers.log(u) * -1.0) * -1.0
        return nn_layers.argmax(logits + g, axis=-1)

    def log_prob(self, value):
        """TPU extension: log p(value) for int class indices."""
        from . import nn as nn_layers, ops as ops_layers
        logits, e, z = self._norm(self.logits)
        logp = logits - ops_layers.log(z)
        oh = nn_layers.one_hot(value, depth=int(self.logits.shape[-1]))
        return nn_layers.reduce_sum(logp * oh, dim=-1)


class MultivariateNormalDiag(Distribution):
    """MVN with diagonal covariance given as a (k, k) diagonal matrix;
    reference layers/distributions.py:494 (entropy + kl)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def _det(self, value):
        from . import nn as nn_layers, tensor as tensor_layers
        shape = list(value.shape)
        ones_all = tensor_layers.ones(shape, "float32")
        eye = tensor_layers.diag(tensor_layers.ones([shape[0]], "float32"))
        return nn_layers.reduce_prod(value + ones_all - eye)

    def _inv(self, value):
        from . import nn as nn_layers, tensor as tensor_layers
        shape = list(value.shape)
        ones_all = tensor_layers.ones(shape, "float32")
        eye = tensor_layers.diag(tensor_layers.ones([shape[0]], "float32"))
        return nn_layers.elementwise_pow(value, ones_all - eye * 2.0)

    def entropy(self):
        from . import ops as ops_layers
        k = int(self.scale.shape[0])
        return (ops_layers.log(self._det(self.scale))
                + k * (1.0 + math.log(2 * math.pi))) * 0.5

    def kl_divergence(self, other):
        from . import nn as nn_layers, ops as ops_layers
        assert isinstance(other, MultivariateNormalDiag)
        tr = nn_layers.reduce_sum(self._inv(other.scale) * self.scale)
        diff = other.loc - self.loc
        quad = nn_layers.matmul(
            nn_layers.matmul(diff, self._inv(other.scale)), diff)
        k = int(self.scale.shape[0])
        ln_cov = ops_layers.log(self._det(other.scale)) \
            - ops_layers.log(self._det(self.scale))
        return (tr + quad - float(k) + ln_cov) * 0.5
