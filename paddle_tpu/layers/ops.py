"""Activation / simple unary layers, generated from the op registry.

Parity: python/paddle/fluid/layers/ops.py (layer_function_generator-produced
wrappers around activation_op.cc kernels).
"""

from ..core.layer_helper import LayerHelper

_UNARY = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softplus",
    "softsign", "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin",
    "round", "reciprocal", "square", "acos", "asin", "atan", "gelu", "erf",
    "log_softmax", "selu", "log", "mish",
]


def _make_unary(op_type):
    def fn(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, {"X": x}, {"Out": out})
        return out
    fn.__name__ = op_type
    fn.__doc__ = f"Parity: fluid.layers.{op_type} (activation_op.cc)."
    return fn


for _name in _UNARY:
    globals()[_name] = _make_unary(_name)


def _make_attr_unary(op_type, attr_names_defaults):
    def fn(x, *args, **kwargs):
        attrs = dict(attr_names_defaults)
        for (k, _), v in zip(attr_names_defaults.items(), args):
            attrs[k] = v
        for k, v in kwargs.items():
            if k in attrs:
                attrs[k] = v
        helper = LayerHelper(op_type, name=kwargs.get("name"))
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, {"X": x}, {"Out": out}, attrs)
        return out
    fn.__name__ = op_type
    return fn


elu = _make_attr_unary("elu", {"alpha": 1.0})
relu6 = _make_attr_unary("relu6", {"threshold": 6.0})
pow = _make_attr_unary("pow", {"factor": 1.0})
stanh = _make_attr_unary("stanh", {"scale_a": 0.67, "scale_b": 1.7159})
hard_sigmoid = _make_attr_unary("hard_sigmoid", {"slope": 0.2, "offset": 0.5})
swish = _make_attr_unary("swish", {"beta": 1.0})
hard_swish = _make_attr_unary("hard_swish", {"threshold": 6.0, "scale": 6.0,
                                             "offset": 3.0})
thresholded_relu = _make_attr_unary("thresholded_relu", {"threshold": 1.0})
hard_shrink = _make_attr_unary("hard_shrink", {"threshold": 0.5})
softshrink = _make_attr_unary("softshrink", {"lambda": 0.5})
soft_relu = _make_attr_unary("soft_relu", {"threshold": 40.0})
brelu = _make_attr_unary("brelu", {"t_min": 0.0, "t_max": 24.0})


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("uniform_random", {}, {"Out": out},
                     {"shape": list(shape), "dtype": dtype, "min": float(min),
                      "max": float(max), "op_seed": helper.next_op_seed()})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("gaussian_random", {}, {"Out": out},
                     {"shape": list(shape), "dtype": dtype,
                      "mean": float(mean), "std": float(std),
                      "op_seed": helper.next_op_seed()})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("gaussian_random_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "mean": float(mean),
                      "std": float(std), "op_seed": helper.next_op_seed()})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("uniform_random", {"Input": input}, {"Out": out},
                     {"shape": list(shape), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "min": float(min),
                      "max": float(max), "op_seed": helper.next_op_seed()})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference("int64", (x.shape[0],))
    helper.append_op("sampling_id", {"X": x}, {"Out": out},
                     {"op_seed": helper.next_op_seed()})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out_shape = tuple(x.shape[:x.ndim - len(shape)]) + tuple(shape)
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("random_crop", {"X": x}, {"Out": out},
                     {"shape": list(shape), "op_seed": helper.next_op_seed()})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None):
    from .nn import cumsum as _cumsum
    return _cumsum(x, axis, exclusive, reverse)
