"""Attention layers.

Parity: python/paddle/fluid/layers/nn.py scaled_dot_product_attention and
the transformer recipes (book machine-translation / models transformer).
TPU-first: attention is a first-class op routed to a Pallas flash-attention
kernel on TPU (ops/attention_ops.py, ops/pallas/flash.py); sequence
parallelism over long contexts uses parallel/ring_attention.py.
"""

from ..core.layer_helper import LayerHelper

__all__ = ["scaled_dot_product_attention", "multi_head_attention",
           "add_position_encoding"]


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, attn_bias=None,
                                 causal=False, segment_ids=None, name=None):
    """Parity: fluid.nets.scaled_dot_product_attention.

    queries/keys/values: (B, T, d_model). Splits into num_heads, attends
    (flash kernel on TPU), merges. Returns (B, Tq, d_model_v)."""
    from . import nn as nn_layers
    helper = LayerHelper("scaled_dot_product_attention", name=name)
    b, tq, dm = queries.shape

    def split_heads(x):
        b_, t, m = x.shape
        r = nn_layers.reshape(x, [b_, t, num_heads, m // num_heads])
        return nn_layers.transpose(r, [0, 2, 1, 3])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    out = helper.create_variable_for_type_inference(queries.dtype,
                                                    tuple(q.shape))
    inputs = {"Q": q, "K": k, "V": v}
    if attn_bias is not None:
        inputs["Bias"] = attn_bias
    if segment_ids is not None:
        inputs["SegmentIds"] = segment_ids
    helper.append_op("scaled_dot_product_attention", inputs, {"Out": out},
                     {"causal": causal})
    merged = nn_layers.transpose(out, [0, 2, 1, 3])
    merged = nn_layers.reshape(merged, [b, tq, values.shape[-1]])
    if dropout_rate:
        merged = nn_layers.dropout(merged, dropout_rate)
    return merged


def multi_head_attention(queries, keys=None, values=None, num_heads=8,
                         d_model=None, attn_bias=None, causal=False,
                         param_attr=None, bias_attr=None, dropout_rate=0.0,
                         segment_ids=None, name=None):
    """Full multi-head block: QKV + output projections fused into one op so
    the TPU path can keep everything in one flash kernel + 4 MXU matmuls."""
    from . import nn as nn_layers
    helper = LayerHelper("multihead_attention", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d_model = d_model or queries.shape[-1]
    dtype = queries.dtype

    def _suffixed(attr, nm):
        # A named ParamAttr must not collapse the four projections into one
        # shared parameter: suffix the name per projection.
        if attr is False or attr is None or getattr(attr, "name", None) is None:
            return attr
        import copy
        a = copy.copy(attr)
        a.name = f"{attr.name}_{nm}"
        return a

    def w(nm):
        return helper.create_parameter(_suffixed(helper.param_attr, nm),
                                       [d_model, d_model], dtype)

    def b(nm):
        if helper.bias_attr is False:
            return None
        return helper.create_parameter(_suffixed(helper.bias_attr, nm + "_b"),
                                       [d_model], dtype, is_bias=True)

    wq, wk, wv, wo = w("q"), w("k"), w("v"), w("o")
    bq, bk, bv, bo = b("q"), b("k"), b("v"), b("o")
    out = helper.create_variable_for_type_inference(
        dtype, tuple(queries.shape[:2]) + (d_model,))
    inputs = {"Query": queries, "WQ": wq, "WK": wk, "WV": wv, "WO": wo}
    if keys is not None:
        inputs["Key"] = keys
    if values is not None:
        inputs["Value"] = values
    for nm, v_ in (("BQ", bq), ("BK", bk), ("BV", bv), ("BO", bo)):
        if v_ is not None:
            inputs[nm] = v_
    if attn_bias is not None:
        inputs["Bias"] = attn_bias
    if segment_ids is not None:
        inputs["SegmentIds"] = segment_ids
    helper.append_op("multihead_attention", inputs, {"Out": out},
                     {"num_heads": num_heads, "causal": causal})
    if dropout_rate:
        out = nn_layers.dropout(out, dropout_rate)
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """Parity: fluid.layers.add_position_encoding (sinusoidal)."""
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    tuple(input.shape))
    helper.append_op("add_position_encoding", {"X": input}, {"Out": out},
                     {"alpha": float(alpha), "beta": float(beta)})
    return out
