"""Data input layers.

Parity: python/paddle/fluid/layers/io.py — fluid.layers.data / fluid.data.
py_reader-style async feeding is provided by paddle_tpu.reader.DataLoader
(C++ prefetch ring), so `data` vars here are plain feed slots.
"""

from ..core.framework import default_main_program
from ..core.layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare a feed variable. append_batch_size prepends -1 (fluid 1.x)."""
    shape = list(shape)
    if append_batch_size:
        if len(shape) == 0 or shape[0] != -1:
            shape = [-1] + shape
    block = default_main_program().global_block()
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            lod_level=lod_level, stop_gradient=stop_gradient,
                            is_data=True)


def fluid_data(name, shape, dtype="float32", lod_level=0):
    """Parity: fluid.data (2.x-style, no implicit batch dim)."""
    return data(name, shape, dtype, lod_level, append_batch_size=False)


class PyReader:
    """Parity: fluid.io.PyReader / layers.py_reader (reader/read ops +
    C++ double-buffer queue). TPU-native: the async prefetch lives in
    paddle_tpu.reader.DataLoader (csrc/prefetch.cc ring); this object just
    owns feed slots and iterates a decorated reader into feed dicts."""

    def __init__(self, feed_list, capacity=64, use_double_buffer=True,
                 iterable=True):
        self.feed_list = list(feed_list)
        self.capacity = capacity
        self._reader = None
        self._places = None
        self._sample_list = False

    def decorate_sample_list_generator(self, reader, places=None):
        """Reader yields LISTS OF SAMPLES per iteration (paddle.batch
        output); a DataFeeder stacks them into batch arrays."""
        self._reader = reader
        self._places = places
        self._sample_list = True

    def decorate_batch_generator(self, reader, places=None):
        """Reader yields already-batched arrays (tuple/list/dict)."""
        self._reader = reader
        self._places = places
        self._sample_list = False

    # reference alias pairs (layers/io.py:515-519): tensor_provider ==
    # batch_generator (pre-batched), paddle_reader == sample_list
    decorate_paddle_reader = decorate_sample_list_generator
    decorate_tensor_provider = decorate_batch_generator

    def __iter__(self):
        from ..reader.dataloader import DataLoader
        if self._reader is None:
            raise RuntimeError("PyReader: call decorate_*_generator first")
        loader = DataLoader(self.feed_list, capacity=self.capacity)
        if self._sample_list:
            loader.set_sample_list_generator(self._reader, self._places)
        else:
            loader.set_batch_generator(self._reader, self._places)
        names = [v.name for v in self.feed_list]
        for batch in loader:
            if isinstance(batch, dict):
                yield batch
            else:
                yield dict(zip(names, batch))

    def start(self):
        pass

    def reset(self):
        pass


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Parity: fluid.layers.py_reader — returns a PyReader whose feed
    slots are freshly created data vars."""
    from ..core import unique_name
    base = name if name is not None else unique_name.generate("py_reader")
    feed_list = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        feed_list.append(fluid_data(
            name=f"{base}_slot_{i}", shape=shape, dtype=dtype))
    return PyReader(feed_list, capacity, use_double_buffer)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """Parity: fluid.layers.create_py_reader_by_data."""
    return PyReader(feed_list, capacity, use_double_buffer)


def double_buffer(reader, place=None, name=None):
    """Parity: fluid.layers.double_buffer — on TPU, device prefetch is owned
    by reader.DataLoader; this is an identity marker for API compat."""
    return reader


def read_file(reader):
    """Parity: fluid.layers.read_file — with PyReader feeding feed dicts,
    the feed slots ARE the read results."""
    return reader.feed_list


def load(out, file_path, load_as_fp16=None):
    """Parity: fluid.layers.load — load a save_op-produced file into `out`.

    The reference appends a C++ load op; here the file (npy/npz single
    array) is read host-side at build time and assigned, which keeps the
    executor's step a pure device program."""
    import numpy as np
    from .tensor import assign
    arr = np.load(file_path, allow_pickle=False)
    if hasattr(arr, "files"):               # npz: single entry
        arr = arr[arr.files[0]]
    if load_as_fp16:
        arr = arr.astype(np.float16)
    return assign(arr, output=out)
