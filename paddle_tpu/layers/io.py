"""Data input layers.

Parity: python/paddle/fluid/layers/io.py — fluid.layers.data / fluid.data.
py_reader-style async feeding is provided by paddle_tpu.reader.DataLoader
(C++ prefetch ring), so `data` vars here are plain feed slots.
"""

from ..core.framework import default_main_program
from ..core.layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare a feed variable. append_batch_size prepends -1 (fluid 1.x)."""
    shape = list(shape)
    if append_batch_size:
        if len(shape) == 0 or shape[0] != -1:
            shape = [-1] + shape
    block = default_main_program().global_block()
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            lod_level=lod_level, stop_gradient=stop_gradient,
                            is_data=True)


def fluid_data(name, shape, dtype="float32", lod_level=0):
    """Parity: fluid.data (2.x-style, no implicit batch dim)."""
    return data(name, shape, dtype, lod_level, append_batch_size=False)
