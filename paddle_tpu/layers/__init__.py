"""fluid.layers-compatible API surface.

Parity: python/paddle/fluid/layers/__init__.py — everything re-exported flat,
so `layers.fc(...)`, `layers.data(...)` etc. work like the reference.
"""

from .io import data, fluid_data
from .nn import *          # noqa: F401,F403
from .tensor import (create_tensor, create_parameter, create_global_var,
                     fill_constant, fill_constant_batch_size_like, assign,
                     zeros, ones, zeros_like, ones_like, sums, linspace,
                     range, eye, diag, reverse, has_inf, has_nan, isfinite)
from .ops import *         # noqa: F401,F403
from .loss import (cross_entropy, softmax_with_cross_entropy,
                   square_error_cost, sigmoid_cross_entropy_with_logits,
                   huber_loss, log_loss, bpr_loss, kldiv_loss, rank_loss,
                   margin_rank_loss, dice_loss, npair_loss, mse_loss,
                   teacher_student_sigmoid_loss, cos_sim, center_loss)
from .metric_op import accuracy, auc, mean_iou
from . import learning_rate_scheduler
from .learning_rate_scheduler import (noam_decay, exponential_decay,
                                      natural_exp_decay, inverse_time_decay,
                                      polynomial_decay, piecewise_decay,
                                      cosine_decay, linear_lr_warmup)
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
