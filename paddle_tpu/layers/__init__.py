"""fluid.layers-compatible API surface.

Parity: python/paddle/fluid/layers/__init__.py — everything re-exported flat,
so `layers.fc(...)`, `layers.data(...)` etc. work like the reference.
"""

from .io import (data, fluid_data, py_reader, create_py_reader_by_data,
                 double_buffer, read_file, load, PyReader)
from .nn import *          # noqa: F401,F403
from .tensor import (tensor_array_to_tensor,
                     create_tensor, create_parameter, create_global_var,
                     fill_constant, fill_constant_batch_size_like, assign,
                     zeros, ones, zeros_like, ones_like, sums, linspace,
                     range, eye, diag, reverse, has_inf, has_nan, isfinite,
                     scatter_nd, strided_slice, unique, unique_with_counts,
                     shard_index, pad_constant_like)
from .ops import *         # noqa: F401,F403
from .loss import (cross_entropy, softmax_with_cross_entropy,
                   square_error_cost, sigmoid_cross_entropy_with_logits,
                   huber_loss, log_loss, bpr_loss, kldiv_loss, rank_loss,
                   margin_rank_loss, dice_loss, npair_loss, mse_loss,
                   teacher_student_sigmoid_loss, cos_sim, center_loss)
from .metric_op import (accuracy, auc, mean_iou, edit_distance,
                        chunk_eval)
from . import distributions
from .distributions import (Uniform, Normal, Categorical,
                            MultivariateNormalDiag)
from . import layer_function_generator
from .layer_function_generator import (deprecated, generate_layer_fn,
                                       generate_activation_fn, autodoc,
                                       templatedoc)
from . import learning_rate_scheduler
from .learning_rate_scheduler import (noam_decay, exponential_decay,
                                      natural_exp_decay, inverse_time_decay,
                                      polynomial_decay, piecewise_decay,
                                      cosine_decay, linear_lr_warmup)
from . import sequence
from .sequence import (sequence_scatter, sequence_topk_avg_pooling,
                       sequence_mask, sequence_pad, sequence_unpad,
                       sequence_pool, sequence_first_step,
                       sequence_last_step, sequence_softmax,
                       sequence_expand, sequence_expand_as,
                       sequence_reverse, sequence_conv, sequence_concat,
                       sequence_slice, sequence_enumerate, sequence_reshape)
from . import control_flow
from .control_flow import (Print, DynamicRNN,
                           reorder_lod_tensor_by_rank,
                           While, Switch, IfElse, StaticRNN, cond, case,
                           switch_case, increment, array_write, array_read,
                           array_length, create_array, less_than, less_equal,
                           greater_than, greater_equal, equal, not_equal,
                           is_empty, autoincreased_step_counter, while_loop)
from . import rnn
from .rnn import (dynamic_lstm, dynamic_lstmp, dynamic_gru, lstm, gru,
                  lstm_unit, gru_unit)
from . import attention
from .attention import (scaled_dot_product_attention, multi_head_attention,
                        add_position_encoding)
from . import beam_search as beam_search_mod
from .beam_search import beam_search, beam_search_decode
from . import detection
from .detection import (prior_box, density_prior_box, box_coder,
                        iou_similarity, multiclass_nms, yolo_box, roi_pool,
                        roi_align, psroi_pool, ssd_loss, multi_box_head,
                        detection_output, yolov3_loss, anchor_generator,
                        bipartite_match, target_assign, box_clip,
                        polygon_box_transform, retinanet_detection_output,
                        sigmoid_focal_loss, distribute_fpn_proposals,
                        collect_fpn_proposals, generate_proposals,
                        rpn_target_assign, retinanet_target_assign,
                        generate_proposal_labels, box_decoder_and_assign,
                        multiclass_nms2, roi_perspective_transform,
                        generate_mask_labels, detection_map)
from .nn import topk as top_k  # fluid exposes both spellings
from . import distributions
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()


def tile(x, repeat_times, name=None):
    """Parity: paddle.tile / fluid expand with per-dim repeats."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("tile", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("tile", {"X": x}, {"Out": out},
                     {"repeat_times": list(repeat_times)})
    return out
