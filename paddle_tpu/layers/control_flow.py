"""Control-flow layers.

Parity: python/paddle/fluid/layers/control_flow.py (While, StaticRNN,
IfElse, Switch, increment, array_read/array_write/array_length, less_than,
equal, ...).

TPU-first: the reference executes sub-blocks with a nested C++ executor per
iteration. Here every construct stays inside ONE traced XLA graph:

  While      -> lax.while_loop   (carry = parent vars the body writes)
  cond/case  -> lax.cond
  StaticRNN  -> lax.scan         (memories = carry, step inputs = xs)
  Switch     -> guarded selects  (the LR-schedule construct)

so there is no per-step host dispatch and XLA can fuse/pipeline the loop
body.
"""

import contextlib

from ..core.framework import Variable, default_main_program
from ..core.layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = [
    "While", "Switch", "IfElse", "StaticRNN", "cond", "case", "switch_case",
    "increment", "array_write", "array_read", "array_length", "create_array",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "is_empty", "autoincreased_step_counter",
]


# -- scalar helpers ---------------------------------------------------------

def increment(x, value=1.0, in_place=True):
    """Parity: fluid.layers.increment (ref layers/control_flow.py)."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        x.dtype, x.shape)
    helper.append_op("increment", {"X": x}, {"Out": out}, {"step": float(value)})
    return out


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", x.shape)
    helper.append_op(op_type, {"X": x, "Y": y}, {"Out": cond})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", ())
    helper.append_op("is_empty", {"X": x}, {"Out": cond})
    return cond


# -- TensorArray ------------------------------------------------------------

def create_array(dtype="float32"):
    """Parity: fluid.layers.create_array (LoDTensorArray). Static-length
    python list during tracing; see ops/control_flow_ops.py."""
    helper = LayerHelper("create_array")
    arr = helper.create_variable_for_type_inference(dtype)
    helper.append_op("create_array", {}, {"Out": arr})
    arr._is_array = True
    return arr


def array_write(x, i, array=None):
    """i must be a python int or a fill_constant var with static value
    (TPU arrays are trace-time structures)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    idx = i if isinstance(i, int) else getattr(i, "_static_value", None)
    helper.append_op("array_write", {"Array": array, "X": x}, {"Out": array},
                     {"static_index": idx} if idx is not None else {})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    idx = i if isinstance(i, int) else getattr(i, "_static_value", 0)
    helper.append_op("array_read", {"Array": array}, {"Out": out},
                     {"static_index": int(idx)})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", ())
    helper.append_op("array_length", {"Array": array}, {"Out": out})
    return out


def _free_vars(blocks, parent_block):
    """Names a set of sub-blocks read from enclosing scope. Needed so the
    executor's dead-code slicer keeps the producing ops: sub-block bodies
    are traced lazily by the structured op, so their reads must surface as
    inputs of the structured op itself. Nested constructs already list
    their own frees as inputs (built inner-first), so one level suffices."""
    free = []
    for block in blocks:
        local = set(block.vars)
        for op in block.ops:
            for n in op.input_names:
                if n not in local and n not in free:
                    free.append(n)
            local |= set(op.output_names)
    return [v for v in (parent_block._find_var_recursive(n) for n in free)
            if v is not None]


# -- While ------------------------------------------------------------------

class While:
    """Parity: fluid.layers.While.

    with while_op.block(): body layers. Vars that exist BEFORE the loop and
    are written inside the body (via layers.assign etc.) become loop carry;
    the condition var must be re-assigned in the body.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.is_test = is_test

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        # carry = parent vars written in the body (+ the condition var)
        carry = []
        for op in sub_block.ops:
            for name in op.output_names:
                if name in sub_block.vars:
                    continue  # block-local temp
                v = parent_block._find_var_recursive(name)
                if v is not None and name not in carry:
                    carry.append(name)
        if self.cond_var.name not in carry:
            carry.append(self.cond_var.name)
        out_vars = [parent_block._find_var_recursive(n) for n in carry]
        parent_block.append_op(
            "while",
            {"Cond": self.cond_var,
             "X": out_vars + _free_vars([sub_block], parent_block)},
            {"Out": out_vars},
            {"sub_block": sub_block.idx, "carry_names": carry,
             "cond_name": self.cond_var.name})


# -- cond / case / switch_case ---------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None):
    """Parity: fluid.layers.cond — functional two-branch conditional.
    Both branches must return matching structures (lax.cond contract)."""
    helper = LayerHelper("cond", name=name)
    program = helper.main_program

    def trace(fn):
        block = program._create_block()
        try:
            rets = fn() if fn is not None else None
        finally:
            program._rollback()
        if rets is None:
            rets = []
        if isinstance(rets, Variable):
            rets = [rets]
        return block, list(rets)

    t_block, t_rets = trace(true_fn)
    f_block, f_rets = trace(false_fn)
    if len(t_rets) != len(f_rets):
        raise ValueError("cond: true_fn and false_fn must return the same "
                         f"number of values ({len(t_rets)} vs {len(f_rets)})")
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in t_rets]
    parent = program.current_block()
    helper.append_op(
        "cond_pair",
        {"Cond": pred, "X": _free_vars([t_block, f_block], parent)},
        {"Out": outs},
        {"true_block": t_block.idx, "false_block": f_block.idx,
         "true_outs": [v.name for v in t_rets],
         "false_outs": [v.name for v in f_rets]})
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def case(pred_fn_pairs, default=None, name=None):
    """Parity: fluid.layers.case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default), name=name)
    if default is None:
        return cond(pred, fn, fn, name=name)
    return cond(pred, fn, default, name=name)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Parity: fluid.layers.switch_case."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    pred_fn_pairs = []
    for idx, fn in pairs:
        idx_var = tensor_layers.fill_constant((), "int64", int(idx))
        pred_fn_pairs.append((equal(branch_index, idx_var), fn))
    if default is None:
        default = pairs[-1][1]
    return case(pred_fn_pairs, default, name=name)


class Switch:
    """Parity: fluid.layers.Switch (used by LR schedules). Sequential
    guarded assignment blocks; first true case wins."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.cases = []          # [(cond_name or None, block_idx)]
        self.target_names = []   # parent vars assigned in any case
        self._inside = False

    @contextlib.contextmanager
    def _case_block(self, condition):
        program = self.helper.main_program
        parent = program.current_block()
        block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        for op in block.ops:
            for name in op.output_names:
                if name in block.vars:
                    continue
                if parent._find_var_recursive(name) is not None \
                        and name not in self.target_names:
                    self.target_names.append(name)
        self.cases.append(
            [condition.name if condition is not None else None, block.idx])

    def case(self, condition):
        return self._case_block(condition)

    def default(self):
        return self._case_block(None)

    @contextlib.contextmanager
    def block(self):
        self.cases = []
        self.target_names = []
        try:
            yield self
        finally:
            parent = self.helper.main_program.current_block()
            out_vars = [parent._find_var_recursive(n) for n in self.target_names]
            cond_vars = [parent._find_var_recursive(c)
                         for c, _ in self.cases if c is not None]
            program = self.helper.main_program
            case_blocks = [program.blocks[i] for _, i in self.cases]
            self.helper.append_op(
                "switch",
                {"Cond": cond_vars,
                 "X": out_vars + _free_vars(case_blocks, parent)},
                {"Out": out_vars},
                {"cases": self.cases, "target_names": self.target_names})


class IfElse:
    """Parity: fluid.layers.IfElse. The reference physically splits the
    batch by the bool mask and runs each sub-batch through its block
    (split_lod_tensor/merge_lod_tensor). TPU-first both blocks run on the
    FULL batch and output() merges rows with where(cond) — identical
    results for row-wise computation, with no dynamic shapes."""

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._true_outs = []
        self._false_outs = []
        self._in_true = None

    @contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        yield
        self._in_true = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        yield
        self._in_true = None

    def input(self, x):
        return x

    def output(self, *outs):
        target = self._true_outs if self._in_true else self._false_outs
        target.extend(outs)

    def __call__(self):
        from . import nn as nn_layers
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            helper = LayerHelper("ifelse_merge")
            out = helper.create_variable_for_type_inference(t.dtype, t.shape)
            helper.append_op("select",
                             {"Condition": self.cond, "X": t, "Y": f},
                             {"Out": out})
            merged.append(out)
        return merged


# -- StaticRNN --------------------------------------------------------------

class StaticRNN:
    """Parity: fluid.layers.StaticRNN — unrolled RNN over axis 0 of its
    step inputs, lowered to ONE lax.scan (ops/control_flow_ops.py
    static_rnn), not T copies of the cell."""

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._block = None
        self._step_inputs = []   # [outer_name, inner_name]
        self._memories = []      # [inner_name, init_name, updated_name]
        self._step_outputs = []  # inner names
        self._out_vars = []
        self._seq_len = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent = program.current_block()
        self._block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
            self._finalize()

    def step_input(self, x):
        """x: (T, B, ...) — sliced along axis 0 each step."""
        if self._seq_len is None:
            self._seq_len = x.shape[0] if x.shape else None
        inner = self._block.create_var(
            name=self.helper.name + ".in." + x.name, dtype=x.dtype,
            shape=tuple(x.shape[1:]))
        self._step_inputs.append([x.name, inner.name])
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               dtype="float32"):
        if init is None:
            if shape is None:
                raise ValueError("StaticRNN.memory needs init or shape")
            # The init constant belongs OUTSIDE the scan body: swap the
            # program's current block to the parent while building it.
            program = self.helper.main_program
            saved = program.current_block_idx
            program.current_block_idx = self._parent.idx
            try:
                init = tensor_layers.fill_constant(shape, dtype, value)
            finally:
                program.current_block_idx = saved
        inner = self._block.create_var(
            name=self.helper.name + ".mem." + init.name, dtype=init.dtype,
            shape=tuple(init.shape))
        self._memories.append([inner.name, init.name, None])
        return inner

    def update_memory(self, mem, var):
        for m in self._memories:
            if m[0] == mem.name:
                m[2] = var.name
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._step_outputs.append(o.name)
        ov = self._parent.create_var(
            name=self.helper.name + ".out." + o.name, dtype=o.dtype,
            shape=(self._seq_len,) + tuple(o.shape))
        self._out_vars.append(ov)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        for m in self._memories:
            if m[2] is None:
                raise ValueError("StaticRNN memory never updated "
                                 "(call update_memory)")
        last_vars = []
        for inner, init_n, _ in self._memories:
            iv = self._parent._find_var_recursive(init_n)
            lv = self._parent.create_var(
                name=self.helper.name + ".last." + inner, dtype=iv.dtype,
                shape=tuple(iv.shape))
            last_vars.append(lv)
        self._last_vars = last_vars
        in_vars = [self._parent._find_var_recursive(n)
                   for n, _ in self._step_inputs]
        init_vars = [self._parent._find_var_recursive(n)
                     for _, n, _ in self._memories]
        self._parent.append_op(
            "static_rnn",
            {"X": in_vars, "Init": init_vars,
             "Free": _free_vars([self._block], self._parent)},
            {"Out": self._out_vars + last_vars},
            {"sub_block": self._block.idx,
             "step_inputs": self._step_inputs,
             "memories": self._memories,
             "step_outputs": self._step_outputs})

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Parity: fluid.layers.autoincreased_step_counter — persistable int64
    counter bumped once per Executor.run (the @LR_DECAY_COUNTER@ mechanism)."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name, dtype="int64", shape=(1,), persistable=True)
    if counter.op is None:
        from .. import initializer as init_mod
        init_mod.ConstantInitializer(float(begin - step))(counter)
        helper.main_program.global_block().prepend_op(
            "increment", {"X": counter}, {"Out": counter},
            {"step": float(step)})
        counter.op = helper.main_program.global_block().ops[0]
        counter.stop_gradient = True
    return counter


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Functional while loop (lax.while_loop semantics with fluid's API
    shape; the 1.5-era `While` block above is the op-graph form)."""
    helper = LayerHelper("while_loop", name=name)
    n = len(loop_vars)
    outs = [helper.create_variable_for_type_inference(
        getattr(v, "dtype", "float32"), getattr(v, "shape", None))
        for v in loop_vars]
    from ..core.framework import Operator
    cond_id = Operator.CALLABLE_TABLE
    key_c = f"while_cond_{id(cond)}"
    key_b = f"while_body_{id(body)}"
    cond_id[key_c] = cond
    cond_id[key_b] = body
    helper.append_op("while_loop", {"X": list(loop_vars)},
                     {"Out": outs}, {"cond_fn": key_c, "body_fn": key_b,
                                     "n_vars": n})
    return outs


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Parity: fluid.layers.Print — logs the tensor from inside the jitted
    step (jax.debug.print host tap; the step remains one XLA executable).
    Returns the input unchanged so it composes like the reference op."""
    helper = LayerHelper("print")
    # the executor's dead-code slicer treats print as a side-effect root
    # (executor.SIDE_EFFECT_OPS), so the common return-value-dropped usage
    # still logs without making the sink var scope state
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("print", {"X": input}, {"Out": out},
                     {"message": message or "",
                      "print_tensor_name": print_tensor_name,
                      "print_tensor_shape": print_tensor_shape,
                      "print_tensor_value": True})
    return input


class DynamicRNN:
    """Parity: fluid.layers.DynamicRNN (ref layers/control_flow.py).

    The reference walks LoD sequences with a shrinking batch (sorted by
    length); TPU-native this is the padded-batch form of the same
    contract (SURVEY.md design decision 4): step inputs are (B, T, ...)
    batch-major plus an explicit `length` tensor, the whole loop lowers
    to ONE lax.scan via StaticRNN, and memory updates freeze once a row's
    length is passed — exactly the LoD semantics, at fixed shapes.
    Outputs come back (B, T, ...) zero-padded past each row's length.
    """

    def __init__(self, name=None):
        self._rnn = StaticRNN(name=name or "dynamic_rnn")
        self._lengths = None
        self._mask_inner = None
        self._maxlen = None
        self._outputs = []

    @contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            yield

    @contextlib.contextmanager
    def _in_parent_block(self):
        # prep ops (transpose, mask) belong OUTSIDE the scan body — same
        # block-switch trick as StaticRNN.memory's init constants
        program = self._rnn.helper.main_program
        saved = program.current_block_idx
        program.current_block_idx = self._rnn._parent.idx
        try:
            yield
        finally:
            program.current_block_idx = saved

    def step_input(self, x, level=0, length=None):
        """x: (B, T, ...); `length` (B,) must accompany the first step
        input (the padded replacement for LoD lod levels)."""
        from . import nn as nn_layers
        from . import sequence as seq_layers
        with self._in_parent_block():
            perm = [1, 0] + list(range(2, len(x.shape)))
            xt = nn_layers.transpose(x, perm=perm)  # (T, B, ...)
            mt = None
            if length is not None and self._mask_inner is None:
                self._lengths = length
                self._maxlen = x.shape[1]
                m = seq_layers.sequence_mask(length, maxlen=self._maxlen,
                                             dtype="float32")   # (B, T)
                mt = nn_layers.transpose(m, perm=[1, 0])         # (T, B)
        inner = self._rnn.step_input(xt)
        if mt is not None:
            self._mask_inner = self._rnn.step_input(mt)
        return inner

    def static_input(self, x):
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32", batch_ref=None):
        return self._rnn.memory(init=init, shape=shape, value=value,
                                dtype=dtype, batch_ref=batch_ref)

    def update_memory(self, ex_mem, new_mem):
        from . import nn as nn_layers
        from .. import layers as L
        if self._mask_inner is not None:
            # new = ex + m * (new - ex): frozen once the row's length passes
            m = nn_layers.unsqueeze(self._mask_inner, axes=[-1])  # (B, 1)
            diff = L.elementwise_sub(new_mem, ex_mem)
            new_mem = L.elementwise_add(ex_mem, L.elementwise_mul(diff, m))
        self._rnn.update_memory(ex_mem, new_mem)

    def output(self, *outputs):
        self._outputs.extend(outputs)
        self._rnn.output(*outputs)

    def __call__(self):
        from . import nn as nn_layers
        from . import sequence as seq_layers
        from .. import layers as L
        outs = self._rnn()
        outs = outs if isinstance(outs, list) else [outs]
        fixed = []
        for o in outs[:len(self._outputs)]:
            perm = [1, 0] + list(range(2, len(o.shape)))
            ob = nn_layers.transpose(o, perm=perm)  # (B, T, ...)
            if self._lengths is not None:
                m = seq_layers.sequence_mask(
                    self._lengths, maxlen=self._maxlen, dtype="float32")
                for _ in range(len(ob.shape) - 2):
                    m = nn_layers.unsqueeze(m, axes=[-1])
                ob = L.elementwise_mul(ob, m)
            fixed.append(ob)
        return fixed[0] if len(fixed) == 1 else fixed


def reorder_lod_tensor_by_rank(x, rank_table):
    """Parity shim: fluid.layers.reorder_lod_tensor_by_rank. The
    length-sorted shrinking-batch execution it supported does not exist
    here (padded batches + masks run every row in lockstep), so no
    reorder is ever needed; returns x unchanged."""
    return x
