"""Detection layers.

Parity: python/paddle/fluid/layers/detection.py (prior_box, multi_box_head,
box_coder, iou_similarity, multiclass_nms, yolo_box, roi_pool/align,
ssd_loss). Kernels in ops/detection_ops.py. Variable-length outputs
(NMS keeps) are static-shape padded with -1 rows — the TPU replacement for
LoD outputs.
"""

from ..core.layer_helper import LayerHelper

__all__ = ["roi_perspective_transform", "generate_mask_labels",
           "generate_proposals", "rpn_target_assign",
           "retinanet_target_assign", "generate_proposal_labels",
           "box_decoder_and_assign", "multiclass_nms2",
           "prior_box", "density_prior_box", "box_coder", "iou_similarity",
           "multiclass_nms", "yolo_box", "roi_pool", "roi_align",
           "psroi_pool", "ssd_loss", "multi_box_head", "detection_output",
           "detection_map"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    variances = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("prior_box", {"Input": input, "Image": image},
                     {"Boxes": boxes, "Variances": variances},
                     {"min_sizes": list(min_sizes),
                      "max_sizes": list(max_sizes or []),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance), "flip": flip,
                      "clip": clip, "step_w": steps[0], "step_h": steps[1],
                      "offset": offset,
                      "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return boxes, variances


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    variances = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("density_prior_box", {"Input": input, "Image": image},
                     {"Boxes": boxes, "Variances": variances},
                     {"densities": list(densities or [1]),
                      "fixed_sizes": list(fixed_sizes or []),
                      "fixed_ratios": list(fixed_ratios or [1.0]),
                      "variances": list(variance), "clip": clip,
                      "step_w": steps[0], "step_h": steps[1],
                      "offset": offset, "flatten_to_2d": flatten_to_2d})
    return boxes, variances


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": prior_box, "TargetBox": target_box}
    attrs = {"code_type": code_type.lower(),
             "box_normalized": box_normalized, "axis": axis}
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            # reference contract: a python list rides the variance attr
            attrs["variance"] = [float(v) for v in prior_box_var]
        else:
            inputs["PriorBoxVar"] = prior_box_var
    # reference output slot name (box_coder_op.cc): OutputBox
    helper.append_op("box_coder", inputs, {"OutputBox": out}, attrs)
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("iou_similarity", {"X": x, "Y": y}, {"Out": out},
                     {"box_normalized": box_normalized})
    return out


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: returns (N, keep_top_k, 6) padded with -1."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op("multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("yolo_box", {"X": x, "ImgSize": img_size},
                     {"Boxes": boxes, "Scores": scores},
                     {"anchors": list(anchors), "class_num": class_num,
                      "conf_thresh": conf_thresh,
                      "downsample_ratio": downsample_ratio,
                      "clip_bbox": clip_bbox})
    return boxes, scores


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, (rois.shape[0], input.shape[1],
                      pooled_height, pooled_width))
    helper.append_op("roi_pool", {"X": input, "ROIs": rois}, {"Out": out},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, (rois.shape[0], input.shape[1],
                      pooled_height, pooled_width))
    helper.append_op("roi_align", {"X": input, "ROIs": rois}, {"Out": out},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale,
                      "sampling_ratio": sampling_ratio})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("psroi_pool", {"X": input, "ROIs": rois}, {"Out": out},
                     {"output_channels": output_channels,
                      "spatial_scale": spatial_scale,
                      "pooled_height": pooled_height,
                      "pooled_width": pooled_width})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             name=None):
    helper = LayerHelper("ssd_loss", name=name)
    out = helper.create_variable_for_type_inference(location.dtype)
    helper.append_op("ssd_loss",
                     {"Location": location, "Confidence": confidence,
                      "GtBox": gt_box, "GtLabel": gt_label,
                      "PriorBox": prior_box},
                     {"Out": out},
                     {"overlap_threshold": overlap_threshold,
                      "neg_pos_ratio": neg_pos_ratio,
                      "background_label": background_label})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """Parity: fluid.layers.multi_box_head — SSD heads over multiple
    feature maps: per-map 3x3 convs predicting loc (4A) + conf (CA), plus
    the matching prior boxes, all flattened and concatenated."""
    from . import nn as nn_layers
    from ..ops.detection_ops import expand_aspect_ratios
    if min_sizes is None:
        # reference formula: evenly spaced ratios between min_ratio/max_ratio
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (num_layer - 2))
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms = ms if isinstance(ms, (list, tuple)) else [ms]
        Ms = max_sizes[i] if max_sizes else []
        Ms = Ms if isinstance(Ms, (list, tuple)) else [Ms]
        ar = aspect_ratios[i]
        ar = ar if isinstance(ar, (list, tuple)) else [ar]
        box, var = prior_box(feat, image, ms, Ms, ar, variance, flip, clip,
                             steps=[step_w[i] if step_w else 0.0,
                                    step_h[i] if step_h else 0.0],
                             offset=offset,
                             min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # prior_box_op.h:94-97: priors per cell = expanded ratios (1.0
        # leads, dedup, flip) x min sizes + ONE sqrt box per max size.
        num_priors = len(ms) * len(expand_aspect_ratios(ar, flip)) + len(Ms)
        loc = nn_layers.conv2d(feat, num_priors * 4, kernel_size,
                               padding=pad, stride=stride)
        conf = nn_layers.conv2d(feat, num_priors * num_classes, kernel_size,
                                padding=pad, stride=stride)
        # Batch dim is symbolic (-1) at graph-build time; the prior count
        # per map is static (H*W*A), so put -1 only on the batch axis.
        n_boxes = feat.shape[2] * feat.shape[3] * num_priors
        loc = nn_layers.reshape(nn_layers.transpose(loc, [0, 2, 3, 1]),
                                [-1, n_boxes, 4])
        conf = nn_layers.reshape(nn_layers.transpose(conf, [0, 2, 3, 1]),
                                 [-1, n_boxes, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(nn_layers.reshape(box, [-1, 4]))
        vars_all.append(nn_layers.reshape(var, [-1, 4]))
    mbox_locs = nn_layers.concat(locs, axis=1)
    mbox_confs = nn_layers.concat(confs, axis=1)
    boxes = nn_layers.concat(boxes_all, axis=0)
    variances = nn_layers.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Parity: fluid.layers.detection_output — decode + softmax + NMS."""
    from . import nn as nn_layers
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    probs = nn_layers.softmax(scores, axis=-1)
    probs_t = nn_layers.transpose(probs, [0, 2, 1])  # (N, C, M)
    return multiclass_nms(decoded, probs_t, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold,
                          background_label=background_label)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """Parity: fluid.layers.yolov3_loss."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype,
                                                     (x.shape[0],))
    ins = {"X": x, "GTBox": gt_box, "GTLabel": gt_label}
    if gt_score is not None:
        ins["GTScore"] = gt_score
    helper.append_op("yolov3_loss", ins, {"Loss": loss},
                     {"anchors": list(anchors),
                      "anchor_mask": list(anchor_mask),
                      "class_num": class_num,
                      "ignore_thresh": ignore_thresh,
                      "downsample_ratio": downsample_ratio,
                      "use_label_smooth": use_label_smooth})
    return loss


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    """Parity: fluid.layers.anchor_generator."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("anchor_generator", name=name)
    anchor_sizes = list(anchor_sizes or [64.0, 128.0, 256.0, 512.0])
    aspect_ratios = list(aspect_ratios or [0.5, 1.0, 2.0])
    a = len(anchor_sizes) * len(aspect_ratios)
    shape = (input.shape[2], input.shape[3], a, 4)
    anchors = helper.create_variable_for_type_inference("float32", shape)
    variances = helper.create_variable_for_type_inference("float32", shape)
    helper.append_op("anchor_generator", {"Input": input},
                     {"Anchors": anchors, "Variances": variances},
                     {"anchor_sizes": list(anchor_sizes or [64, 128, 256, 512]),
                      "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
                      "variances": list(variance),
                      "stride": list(stride or [16.0, 16.0]),
                      "offset": offset})
    return anchors, variances


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Parity: fluid.layers.bipartite_match."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op("bipartite_match", {"DistMat": dist_matrix},
                     {"ColToRowMatchIndices": idx,
                      "ColToRowMatchDist": dist},
                     {"match_type": match_type or "bipartite",
                      "dist_threshold": (0.5 if dist_threshold is None
                                         else float(dist_threshold))})
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Parity: fluid.layers.target_assign."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_wt = helper.create_variable_for_type_inference("float32")
    helper.append_op("target_assign",
                     {"X": input, "MatchIndices": matched_indices},
                     {"Out": out, "OutWeight": out_wt},
                     {"mismatch_value": mismatch_value})
    return out, out_wt


def box_clip(input, im_info, name=None):
    """Parity: fluid.layers.box_clip."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("box_clip", {"Input": input, "ImInfo": im_info},
                     {"Output": out}, {})
    return out


def polygon_box_transform(input, name=None):
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("polygon_box_transform", {"Input": input},
                     {"Output": out}, {})
    return out


def retinanet_detection_output(bboxes, scores, im_info=None,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """Parity: fluid.layers.retinanet_detection_output (decode+threshold on
    device; NMS host-side like detection_output)."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("retinanet_detection_output")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("retinanet_detection_output",
                     {"BBoxes": list(bboxes), "Scores": list(scores)},
                     {"Out": out},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold})
    return out


def sigmoid_focal_loss(x, label, fg_num=None, gamma=2.0, alpha=0.25):
    """Parity: fluid.layers.sigmoid_focal_loss."""
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    ins = {"X": x, "Label": label}
    if fg_num is not None:
        ins["FgNum"] = fg_num
    helper.append_op("sigmoid_focal_loss", ins, {"Out": out},
                     {"gamma": gamma, "alpha": alpha})
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("distribute_fpn_proposals")
    n_lvl = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference(fpn_rois.dtype)
            for _ in range(n_lvl)]
    idx = helper.create_variable_for_type_inference("int32")
    helper.append_op("distribute_fpn_proposals", {"FpnRois": fpn_rois},
                     {"MultiFpnRois": outs, "RestoreIndex": idx},
                     {"min_level": min_level, "max_level": max_level,
                      "refer_level": refer_level, "refer_scale": refer_scale})
    return outs, idx


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    from ..core.layer_helper import LayerHelper
    helper = LayerHelper("collect_fpn_proposals")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("collect_fpn_proposals",
                     {"MultiLevelRois": list(multi_rois),
                      "MultiLevelScores": list(multi_scores)},
                     {"FpnRois": out}, {"post_nms_topN": post_nms_top_n})
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """Parity: fluid.layers.generate_proposals. Static outputs:
    rois (N, post_nms_top_n, 4) padded with -1 rows + probs."""
    helper = LayerHelper("generate_proposals", name=name)
    n = scores.shape[0]
    rois = helper.create_variable_for_type_inference(
        "float32", (n, post_nms_top_n, 4))
    probs = helper.create_variable_for_type_inference(
        "float32", (n, post_nms_top_n, 1))
    helper.append_op("generate_proposals",
                     {"Scores": scores, "BboxDeltas": bbox_deltas,
                      "ImInfo": im_info, "Anchors": anchors,
                      "Variances": variances},
                     {"RpnRois": rois, "RpnRoiProbs": probs},
                     {"pre_nms_topN": pre_nms_top_n,
                      "post_nms_topN": post_nms_top_n,
                      "nms_thresh": nms_thresh, "min_size": min_size})
    return rois, probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """Parity: fluid.layers.rpn_target_assign. Static outputs per image:
    rpn_batch_size_per_im sampled rows; padding rows carry zero weight
    (the LoD-free replacement for the reference's index outputs)."""
    helper = LayerHelper("rpn_target_assign")
    n = gt_boxes.shape[0]
    r = rpn_batch_size_per_im
    c = cls_logits.shape[-1]
    sp = helper.create_variable_for_type_inference("float32", (n, r, c))
    lp = helper.create_variable_for_type_inference("float32", (n, r, 4))
    tl = helper.create_variable_for_type_inference("int32", (n, r, 1))
    tb = helper.create_variable_for_type_inference("float32", (n, r, 4))
    iw = helper.create_variable_for_type_inference("float32", (n, r, 4))
    sw = helper.create_variable_for_type_inference("float32", (n, r, 1))
    helper.append_op("rpn_target_assign",
                     {"BboxPred": bbox_pred, "ClsLogits": cls_logits,
                      "Anchor": anchor_box, "GtBoxes": gt_boxes},
                     {"PredictedScores": sp, "PredictedLocation": lp,
                      "TargetLabel": tl, "TargetBBox": tb,
                      "BBoxInsideWeight": iw, "ScoreWeight": sw},
                     {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                      "rpn_fg_fraction": rpn_fg_fraction,
                      "rpn_positive_overlap": rpn_positive_overlap,
                      "rpn_negative_overlap": rpn_negative_overlap})
    # extension over the reference 5-tuple: `sw` masks padded sample rows
    # (weight 0) — the LoD-free replacement for shrinking index outputs;
    # weight the objectness CE with it.
    return sp, lp, tl, tb, iw, sw


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels=None, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    """Parity: fluid.layers.retinanet_target_assign — every anchor is
    labeled (focal loss consumes all); weights carry the fg/valid masks."""
    helper = LayerHelper("retinanet_target_assign")
    n = gt_boxes.shape[0]
    a = anchor_box.shape[0]
    c = cls_logits.shape[-1]
    sp = helper.create_variable_for_type_inference("float32", (n, a, c))
    lp = helper.create_variable_for_type_inference("float32", (n, a, 4))
    tl = helper.create_variable_for_type_inference("int32", (n, a, 1))
    tb = helper.create_variable_for_type_inference("float32", (n, a, 4))
    iw = helper.create_variable_for_type_inference("float32", (n, a, 4))
    sw = helper.create_variable_for_type_inference("float32", (n, a, 1))
    inputs = {"BboxPred": bbox_pred, "ClsLogits": cls_logits,
              "Anchor": anchor_box, "GtBoxes": gt_boxes}
    if gt_labels is not None:
        inputs["GtLabels"] = gt_labels
    helper.append_op("retinanet_target_assign", inputs,
                     {"PredictedScores": sp, "PredictedLocation": lp,
                      "TargetLabel": tl, "TargetBBox": tb,
                      "BBoxInsideWeight": iw, "ScoreWeight": sw},
                     {"retinanet": True,
                      "rpn_positive_overlap": positive_overlap,
                      "rpn_negative_overlap": negative_overlap})
    return sp, lp, tl, tb, iw, sw


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd=None,
                             gt_boxes=None, im_info=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Parity: fluid.layers.generate_proposal_labels. Static outputs
    (N, batch_size_per_im, ...); label -1 marks padding rows.
    Reference-default knobs: fg_thresh 0.25, bbox_reg_weights
    [0.1, 0.1, 0.2, 0.2]; class_nums has no default (required)."""
    if class_nums is None:
        raise ValueError(
            "generate_proposal_labels: class_nums is required (the "
            "reference default of None fails the same way)")
    helper = LayerHelper("generate_proposal_labels")
    n = rpn_rois.shape[0]
    r = batch_size_per_im
    rois = helper.create_variable_for_type_inference("float32", (n, r, 4))
    labels = helper.create_variable_for_type_inference("int32", (n, r, 1))
    tgts = helper.create_variable_for_type_inference(
        "float32", (n, r, 4 * class_nums))
    iw = helper.create_variable_for_type_inference(
        "float32", (n, r, 4 * class_nums))
    ow = helper.create_variable_for_type_inference(
        "float32", (n, r, 4 * class_nums))
    helper.append_op("generate_proposal_labels",
                     {"RpnRois": rpn_rois, "GtClasses": gt_classes,
                      "GtBoxes": gt_boxes},
                     {"Rois": rois, "LabelsInt32": labels,
                      "BboxTargets": tgts, "BboxInsideWeights": iw,
                      "BboxOutsideWeights": ow},
                     {"batch_size_per_im": batch_size_per_im,
                      "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
                      "bg_thresh_hi": bg_thresh_hi,
                      "bg_thresh_lo": bg_thresh_lo,
                      "bbox_reg_weights": list(bbox_reg_weights)
                      if bbox_reg_weights else None,
                      "class_nums": class_nums})
    return rois, labels, tgts, iw, ow


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=None, name=None):
    """Parity: fluid.layers.box_decoder_and_assign."""
    helper = LayerHelper("box_decoder_and_assign", name=name)
    r, c4 = target_box.shape
    decoded = helper.create_variable_for_type_inference("float32", (r, c4))
    assigned = helper.create_variable_for_type_inference("float32", (r, 4))
    inputs = {"PriorBox": prior_box, "TargetBox": target_box,
              "BoxScore": box_score}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = prior_box_var
    helper.append_op("box_decoder_and_assign", inputs,
                     {"DecodeBox": decoded, "OutputAssignBox": assigned},
                     {"box_clip": box_clip})
    return decoded, assigned


def multiclass_nms2(bboxes, scores, score_threshold=0.01, nms_top_k=64,
                    keep_top_k=100, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0, return_index=False,
                    name=None):
    """Parity: fluid.layers.multiclass_nms2 — multiclass_nms plus the
    kept-row index channel."""
    helper = LayerHelper("multiclass_nms2", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference("int32")
    helper.append_op("multiclass_nms2",
                     {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out, "Index": index},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    if return_index:
        return out, index
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """Parity: fluid.layers.roi_perspective_transform. rois (N, R, 8)
    quadrilaterals; returns (N, R, C, th, tw)."""
    helper = LayerHelper("roi_perspective_transform")
    n, c = input.shape[0], input.shape[1]
    r = rois.shape[1]
    out = helper.create_variable_for_type_inference(
        input.dtype, (n, r, c, transformed_height, transformed_width))
    helper.append_op("roi_perspective_transform",
                     {"X": input, "ROIs": rois}, {"Out": out},
                     {"transformed_height": transformed_height,
                      "transformed_width": transformed_width,
                      "spatial_scale": spatial_scale})
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         poly_lengths=None):
    """Parity: fluid.layers.generate_mask_labels (Mask R-CNN targets).
    Padded form: gt_segms (N, G, P, 2) one polygon per instance +
    poly_lengths (N, G). MaskInt32 is (N, R, num_classes*res*res) with
    -1 = ignore."""
    helper = LayerHelper("generate_mask_labels")
    n, r = rois.shape[0], rois.shape[1]
    mask_rois = helper.create_variable_for_type_inference(
        "float32", (n, r, 4))
    has_mask = helper.create_variable_for_type_inference("int32", (n, r, 1))
    masks = helper.create_variable_for_type_inference(
        "int32", (n, r, num_classes * resolution * resolution))
    inputs = {"ImInfo": im_info, "GtClasses": gt_classes,
              "GtSegms": gt_segms, "Rois": rois,
              "LabelsInt32": labels_int32}
    if is_crowd is not None:
        inputs["IsCrowd"] = is_crowd
    if poly_lengths is not None:
        inputs["PolyLengths"] = poly_lengths
    helper.append_op("generate_mask_labels", inputs,
                     {"MaskRois": mask_rois, "RoiHasMaskInt32": has_mask,
                      "MaskInt32": masks},
                     {"num_classes": num_classes, "resolution": resolution})
    return mask_rois, has_mask, masks


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """Parity: fluid.layers.detection_map (ref detection.py:1002).
    Current-batch VOC mAP via the host-callback detection_map op;
    multi-batch streaming accumulation (the reference's in-graph
    PosCount/TruePos/FalsePos states) lives host-side in
    metrics.DetectionMAP — pass detections there for eval loops."""
    if input_states is not None or out_states is not None:
        raise NotImplementedError(
            "detection_map in-graph accumulator states are not ported: "
            "stream batches through metrics.DetectionMAP instead "
            "(host-side accumulate, same VOC math)")
    helper = LayerHelper("detection_map")
    out = helper.create_variable_for_type_inference("float32", (1,))
    helper.append_op("detection_map",
                     {"DetectRes": detect_res, "Label": label},
                     {"MAP": out},
                     {"overlap_threshold": overlap_threshold,
                      "ap_version": ap_version,
                      "class_num": class_num,
                      "background_label": background_label,
                      "evaluate_difficult": evaluate_difficult})
    return out
