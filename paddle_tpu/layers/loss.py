"""Loss layers.

Parity: the loss functions of python/paddle/fluid/layers/nn.py
(cross_entropy, softmax_with_cross_entropy, square_error_cost, ...).
"""

from ..core.layer_helper import LayerHelper


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    shape = tuple(input.shape[:-1]) + (1,)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("cross_entropy", {"X": input, "Label": label},
                     {"Y": out},
                     {"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(logits.dtype, logits.shape)
    loss_shape = tuple(logits.shape[:-1]) + (1,)
    loss = helper.create_variable_for_type_inference(logits.dtype, loss_shape)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label},
                     {"Softmax": softmax, "Loss": loss},
                     {"soft_label": soft_label, "ignore_index": ignore_index,
                      "axis": axis})
    if return_softmax:
        return loss, softmax
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    sub = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("square_error_cost", {"X": input, "Y": label},
                     {"Out": out, "sub_result": sub})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": label}, {"Out": out},
                     {"ignore_index": ignore_index, "normalize": normalize})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    resid = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("huber_loss", {"X": input, "Y": label},
                     {"Out": out, "Residual": resid}, {"delta": float(delta)})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("log_loss", {"Predicted": input, "Labels": label},
                     {"Loss": out}, {"epsilon": epsilon})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], 1))
    helper.append_op("bpr_loss", {"X": input, "Label": label}, {"Y": out})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    shape = () if reduction in ("mean", "sum", "batchmean") else x.shape
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("kldiv_loss", {"X": x, "Target": target}, {"Loss": out},
                     {"reduction": reduction})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    helper.append_op("rank_loss",
                     {"Label": label, "Left": left, "Right": right},
                     {"Out": out})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    act = helper.create_variable_for_type_inference(left.dtype, left.shape)
    helper.append_op("margin_rank_loss",
                     {"Label": label, "X1": left, "X2": right},
                     {"Out": out, "Activated": act}, {"margin": margin})
    return out


def dice_loss(input, label, epsilon=1e-5):
    helper = LayerHelper("dice_loss")
    out = helper.create_variable_for_type_inference(input.dtype, ())
    helper.append_op("dice_loss", {"X": input, "Label": label}, {"Out": out},
                     {"epsilon": epsilon})
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss")
    out = helper.create_variable_for_type_inference(anchor.dtype, ())
    helper.append_op("npair_loss",
                     {"Anchor": anchor, "Positive": positive, "Labels": labels},
                     {"Out": out}, {"l2_reg": l2_reg})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss")
    out = helper.create_variable_for_type_inference(input.dtype, ())
    helper.append_op("mse_loss", {"X": input, "Y": label}, {"Out": out})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    from .nn import smooth_l1 as _impl
    return _impl(x, y, inside_weight, outside_weight, sigma)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Parity: fluid.layers.teacher_student_sigmoid_loss. The soft_max
    bounds shape only the reference's HAND-WRITTEN gradient clamp; here
    autodiff differentiates the exact forward, so they are accepted for
    signature parity but have no effect (documented deviation)."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], 1))
    helper.append_op("teacher_student_sigmoid_loss",
                     {"X": input, "Label": label}, {"Y": out},
                     {"soft_max_up_bound": soft_max_up_bound,
                      "soft_max_lower_bound": soft_max_lower_bound})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype, (X.shape[0], 1))
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", {"X": X, "Y": Y},
                     {"Out": out, "XNorm": xn, "YNorm": yn})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    from .. import initializer as init_mod
    helper = LayerHelper("center_loss", param_attr=param_attr)
    centers = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes, input.shape[-1]],
        dtype=input.dtype,
        default_initializer=init_mod.ConstantInitializer(0.0))
    centers.trainable = False
    from .tensor import fill_constant
    alpha_var = fill_constant([1], "float32", alpha)
    out = helper.create_variable_for_type_inference(input.dtype, (input.shape[0], 1))
    diff = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("center_loss",
                     {"X": input, "Label": label, "Centers": centers,
                      "CenterUpdateRate": alpha_var},
                     {"Loss": out, "SampleCenterDiff": diff,
                      "CentersOut": centers},
                     {"need_update": update_center})
    return out
