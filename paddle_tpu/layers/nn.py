"""Neural-network layers (declarative API).

Parity: python/paddle/fluid/layers/nn.py (fc, embedding, conv2d, pool2d,
batch_norm, layer_norm, dropout, ...). Each function appends ops to the
default main program; kernels live in paddle_tpu/ops/ as fused-friendly JAX.
"""

import numpy as np

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from .. import initializer as init_mod


def _prod(xs):
    r = 1
    for x in xs:
        r *= int(x)
    return r


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Parity: fluid.layers.fc (ref python/paddle/fluid/layers/nn.py:fc).
    mul lowers onto the MXU; bias+act fuse into the matmul epilogue."""
    helper = LayerHelper("fc", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    dtype = inputs[0].dtype
    mul_results = []
    for inp, p_attr in zip(inputs, (helper.param_attr if isinstance(helper.param_attr, list)
                                    else [helper.param_attr] * len(inputs))):
        in_features = _prod(inp.shape[num_flatten_dims:])
        w = helper.create_parameter(attr=p_attr, shape=[in_features, size],
                                    dtype=dtype)
        out_shape = tuple(inp.shape[:num_flatten_dims]) + (size,)
        tmp = helper.create_variable_for_type_inference(dtype, out_shape)
        helper.append_op("mul", {"X": inp, "Y": w}, {"Out": tmp},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype, mul_results[0].shape)
        helper.append_op("sum", {"X": mul_results}, {"Out": pre_bias})
    pre_act = _append_bias(helper, pre_bias, size, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def _append_bias(helper, pre_bias, size, dim_start=1):
    bias_attr = helper.bias_attr
    if bias_attr is False:
        return pre_bias
    b = helper.create_parameter(attr=bias_attr, shape=[size],
                                dtype=pre_bias.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(pre_bias.dtype, pre_bias.shape)
    helper.append_op("elementwise_add", {"X": pre_bias, "Y": b}, {"Out": out},
                     {"axis": dim_start})
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """Parity: fluid.layers.embedding. is_sparse is accepted but moot: on TPU
    the gather rides HBM and grads flow as dense rows (XLA scatter-add)."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(attr=helper.param_attr, shape=list(size),
                                dtype=dtype,
                                default_initializer=init_mod.XavierInitializer())
    in_shape = tuple(input.shape)
    if in_shape and in_shape[-1] == 1:
        in_shape = in_shape[:-1]
    out = helper.create_variable_for_type_inference(dtype, in_shape + (size[1],))
    pidx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op("lookup_table", {"W": w, "Ids": input}, {"Out": out},
                     {"padding_idx": pidx, "is_sparse": is_sparse})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    shape = tuple(input.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    out = helper.create_variable_for_type_inference("float32", shape + (depth,))
    helper.append_op("one_hot", {"X": input}, {"Out": out}, {"depth": depth})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x and len(xs) >= 2:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) >= 2:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    out_shape = tuple(xs[:-1] + ys[-1:]) if len(ys) >= 2 else tuple(xs[:-1])
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("matmul", {"X": x, "Y": y}, {"Out": out},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": float(alpha)})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out_shape = tuple(x.shape[:x_num_col_dims]) + tuple(y.shape[y_num_col_dims:])
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("mul", {"X": x, "Y": y}, {"Out": out},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def _conv_out_size(in_size, k, pad, stride, dilation=1):
    if in_size is None or in_size < 0:
        return -1
    return (in_size + 2 * pad - (dilation * (k - 1) + 1)) // stride + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """Parity: fluid.layers.conv2d (nn.py:conv2d). use_cudnn ignored: XLA
    picks the TPU conv algorithm."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    groups = groups or 1
    fsize = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    num_channels = input.shape[1]
    filter_shape = [num_filters, num_channels // groups] + fsize
    fan_in = (num_channels // groups) * fsize[0] * fsize[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, std))
    h = _conv_out_size(input.shape[2], fsize[0], padding[0], stride[0], dilation[0])
    wd = _conv_out_size(input.shape[3], fsize[1], padding[1], stride[1], dilation[1])
    out_shape = (input.shape[0], num_filters, h, wd)
    pre_bias = helper.create_variable_for_type_inference(input.dtype, out_shape)
    op_type = "depthwise_conv2d" if (groups == num_channels and num_filters % num_channels == 0) else "conv2d"
    helper.append_op(op_type, {"Input": input, "Filter": w},
                     {"Output": pre_bias},
                     {"strides": stride, "paddings": padding,
                      "dilations": dilation, "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype, out_shape)
        helper.append_op("elementwise_add", {"X": pre_bias, "Y": b},
                         {"Out": pre_act}, {"axis": 1})
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    groups = groups or 1
    fsize = _pair(filter_size, 3)
    stride = _pair(stride, 3)
    padding = _pair(padding, 3)
    dilation = _pair(dilation, 3)
    num_channels = input.shape[1]
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_filters, num_channels // groups] + fsize, dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(
            0.0, (2.0 / ((num_channels // groups) * _prod(fsize))) ** 0.5))
    dims = [_conv_out_size(input.shape[2 + i], fsize[i], padding[i], stride[i],
                           dilation[i]) for i in range(3)]
    out_shape = (input.shape[0], num_filters) + tuple(dims)
    pre_bias = helper.create_variable_for_type_inference(input.dtype, out_shape)
    helper.append_op("conv3d", {"Input": input, "Filter": w},
                     {"Output": pre_bias},
                     {"strides": stride, "paddings": padding,
                      "dilations": dilation, "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype, out_shape)
        helper.append_op("elementwise_add", {"X": pre_bias, "Y": b},
                         {"Out": pre_act}, {"axis": 1})
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    groups = groups or 1
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    num_channels = input.shape[1]
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size or filter_size required")
        output_size = _pair(output_size)
        filter_size = [output_size[i] - (input.shape[2 + i] - 1) * stride[i] +
                       2 * padding[i] for i in range(2)]
    fsize = _pair(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_channels, num_filters // groups] + fsize, dtype=input.dtype,
        default_initializer=init_mod.XavierInitializer())
    oh = (input.shape[2] - 1) * stride[0] - 2 * padding[0] + dilation[0] * (fsize[0] - 1) + 1
    ow = (input.shape[3] - 1) * stride[1] - 2 * padding[1] + dilation[1] * (fsize[1] - 1) + 1
    out_shape = (input.shape[0], num_filters, oh, ow)
    pre_bias = helper.create_variable_for_type_inference(input.dtype, out_shape)
    helper.append_op("conv2d_transpose", {"Input": input, "Filter": w},
                     {"Output": pre_bias},
                     {"strides": stride, "paddings": padding,
                      "dilations": dilation, "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype, out_shape)
        helper.append_op("elementwise_add", {"X": pre_bias, "Y": b},
                         {"Out": pre_act}, {"axis": 1})
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    ksize = _pair(pool_size)
    stride = _pair(pool_stride)
    padding = _pair(pool_padding)
    if global_pooling:
        out_shape = tuple(input.shape[:2]) + (1, 1)
    else:
        oh = _conv_out_size(input.shape[2], ksize[0], padding[0], stride[0])
        ow = _conv_out_size(input.shape[3], ksize[1], padding[1], stride[1])
        out_shape = tuple(input.shape[:2]) + (oh, ow)
    out = helper.create_variable_for_type_inference(input.dtype, out_shape)
    helper.append_op("pool2d", {"X": input}, {"Out": out},
                     {"pooling_type": pool_type, "ksize": ksize,
                      "strides": stride, "paddings": padding,
                      "global_pooling": global_pooling, "exclusive": exclusive})
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool3d", name=name)
    ksize = _pair(pool_size, 3)
    stride = _pair(pool_stride, 3)
    padding = _pair(pool_padding, 3)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool3d", {"X": input}, {"Out": out},
                     {"pooling_type": pool_type, "ksize": ksize,
                      "strides": stride, "paddings": padding,
                      "global_pooling": global_pooling, "exclusive": exclusive})
    return out


def _adaptive_pool(op_type, input, psize, pool_type, require_index, name):
    """Shared adaptive_pool2d/3d body: validation per the reference
    contract (ref nn.py:3140-3148) + optional argmax Mask output."""
    if pool_type not in ("max", "avg"):
        raise ValueError(
            "Unknown pool_type: '%s'. It can only be 'max' or 'avg'."
            % str(pool_type))
    if pool_type == "avg" and require_index:
        raise ValueError(
            "invalid setting 'require_index' true when 'pool_type' is "
            "'avg'.")
    helper = LayerHelper(op_type, name=name)
    shape = tuple(input.shape[:2]) + tuple(psize)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    outs = {"Out": out}
    if require_index:
        mask = helper.create_variable_for_type_inference("int32", shape)
        outs["Mask"] = mask
    helper.append_op(op_type, {"X": input}, outs,
                     {"pool_size": list(psize), "pooling_type": pool_type,
                      "require_index": require_index})
    return (out, outs["Mask"]) if require_index else out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Parity: fluid.layers.adaptive_pool2d (ref nn.py:3069): floor/ceil
    windows; require_index additionally returns the argmax Mask (flat
    index into the input plane) and is invalid for avg pooling."""
    return _adaptive_pool("adaptive_pool2d", input, _pair(pool_size),
                          pool_type, require_index, name)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """Parity: fluid.layers.batch_norm. Running stats are persistable vars
    updated in the same jitted step (functional in-place)."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = "float32"
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                   dtype=dtype, is_bias=True)
    from ..core import unique_name as un
    mean_name = moving_mean_name or un.generate(helper.name + ".mean")
    var_name = moving_variance_name or un.generate(helper.name + ".variance")
    mean = helper.create_or_get_global_variable(mean_name, shape=(c,),
                                                dtype=dtype, persistable=True)
    variance = helper.create_or_get_global_variable(var_name, shape=(c,),
                                                    dtype=dtype, persistable=True)
    mean.stop_gradient = True
    variance.stop_gradient = True
    init_mod.ConstantInitializer(0.0)(mean)
    init_mod.ConstantInitializer(1.0)(variance)
    saved_mean = helper.create_variable_for_type_inference(dtype)
    saved_var = helper.create_variable_for_type_inference(dtype)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op(
        "batch_norm",
        {"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
         "Variance": variance},
        {"Y": out, "MeanOut": mean, "VarianceOut": variance,
         "SavedMean": saved_mean, "SavedVariance": saved_var},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout, "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    norm_size = _prod(input.shape[begin_norm_axis:])
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr, shape=[norm_size], dtype="float32",
            default_initializer=init_mod.ConstantInitializer(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[norm_size],
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mean = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    helper.append_op("layer_norm", inputs,
                     {"Y": out, "Mean": mean, "Variance": var},
                     {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": input}
    if helper.param_attr is not False:
        inputs["Scale"] = helper.create_parameter(
            attr=helper.param_attr, shape=[c], dtype="float32",
            default_initializer=init_mod.ConstantInitializer(1.0))
    if helper.bias_attr is not False:
        inputs["Bias"] = helper.create_parameter(
            attr=helper.bias_attr, shape=[c], dtype="float32", is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mean = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    helper.append_op("group_norm", inputs,
                     {"Y": out, "Mean": mean, "Variance": var},
                     {"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = input.shape[1]
    inputs = {"X": input}
    if helper.param_attr is not False:
        inputs["Scale"] = helper.create_parameter(
            attr=helper.param_attr, shape=[c], dtype="float32",
            default_initializer=init_mod.ConstantInitializer(1.0))
    if helper.bias_attr is not False:
        inputs["Bias"] = helper.create_parameter(
            attr=helper.bias_attr, shape=[c], dtype="float32", is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("instance_norm", inputs, {"Y": out}, {"epsilon": epsilon})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w = _prod(weight.shape) // h
    u = helper.create_parameter(attr=None, shape=[h], dtype="float32",
                                default_initializer=init_mod.NormalInitializer(0, 1))
    v = helper.create_parameter(attr=None, shape=[w], dtype="float32",
                                default_initializer=init_mod.NormalInitializer(0, 1))
    u.trainable = False
    v.trainable = False
    out = helper.create_variable_for_type_inference(weight.dtype, weight.shape)
    # U/V update in place each step (the reference kernel mutates its
    # U/V inputs), so the power iteration converges across steps
    helper.append_op("spectral_norm", {"Weight": weight, "U": u, "V": v},
                     {"Out": out, "UOut": u, "VOut": v},
                     {"dim": dim, "power_iters": power_iters, "eps": eps})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    mask = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("dropout", {"X": x}, {"Out": out, "Mask": mask},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "dropout_implementation": dropout_implementation,
                      "op_seed": helper.next_op_seed()})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("l2_normalize", {"X": x}, {"Out": out, "Norm": norm},
                     {"axis": axis, "epsilon": epsilon})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    inputs = {"X": label}
    if prior_dist is not None:
        inputs["PriorDist"] = prior_dist
    out = helper.create_variable_for_type_inference(dtype, label.shape)
    helper.append_op("label_smooth", inputs, {"Out": out}, {"epsilon": epsilon})
    return out


# ---- reductions ------------------------------------------------------------

def _reduce_shape(shape, dim, keep_dim):
    if dim is None:
        return () if not keep_dim else tuple(1 for _ in shape)
    dims = [d % len(shape) for d in (dim if isinstance(dim, (list, tuple)) else [dim])]
    if keep_dim:
        return tuple(1 if i in dims else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in dims)


def _make_reduce(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(
            input.dtype, _reduce_shape(input.shape, dim, keep_dim))
        attrs = {"keep_dim": keep_dim}
        if dim is None:
            attrs["reduce_all"] = True
            attrs["dim"] = [0]
        else:
            attrs["dim"] = dim if isinstance(dim, (list, tuple)) else [dim]
        helper.append_op(op_type, {"X": input}, {"Out": out}, attrs)
        return out
    fn.__name__ = op_type
    return fn


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")
reduce_all = _make_reduce("reduce_all")
reduce_any = _make_reduce("reduce_any")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, ())
    helper.append_op("mean", {"X": x}, {"Out": out})
    return out


# ---- elementwise -----------------------------------------------------------

def _make_elementwise(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
        out = helper.create_variable_for_type_inference(x.dtype, shape)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, {"axis": axis})
        return helper.append_activation(out)
    fn.__name__ = op_type
    return fn


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")
elementwise_mod = _make_elementwise("elementwise_mod")
elementwise_floordiv = _make_elementwise("elementwise_floordiv")


def _make_logical(op_type, binary=True):
    if binary:
        def fn(x, y, out=None, name=None):
            helper = LayerHelper(op_type, name=name)
            out = out or helper.create_variable_for_type_inference("bool", x.shape)
            helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out})
            return out
    else:
        def fn(x, out=None, name=None):
            helper = LayerHelper(op_type, name=name)
            out = out or helper.create_variable_for_type_inference("bool", x.shape)
            helper.append_op(op_type, {"X": x}, {"Out": out})
            return out
    fn.__name__ = op_type
    return fn


logical_and = _make_logical("logical_and")
logical_or = _make_logical("logical_or")
logical_xor = _make_logical("logical_xor")
logical_not = _make_logical("logical_not", binary=False)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("clip", {"X": x}, {"Out": out},
                     {"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("clip_by_norm", {"X": x}, {"Out": out},
                     {"max_norm": float(max_norm)})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("scale", {"X": x}, {"Out": out},
                     {"scale": float(scale), "bias": float(bias),
                      "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def sum(x, name=None):
    helper = LayerHelper("sum", name=name)
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype, xs[0].shape)
    helper.append_op("sum", {"X": xs}, {"Out": out})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    attrs = {"exclusive": exclusive, "reverse": reverse}
    if axis is None:
        attrs["flatten"] = True
        attrs["axis"] = 0
    else:
        attrs["axis"] = axis
    helper.append_op("cumsum", {"X": x}, {"Out": out}, attrs)
    return out


# ---- shape manipulation (also exposed from layers.tensor) ------------------

def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", act=act, name=name)
    out_shape = list(shape)
    known = _prod([s for s in out_shape if s > 0]) or 1
    if -1 in out_shape and all(s >= 0 for s in x.shape):
        total = _prod(x.shape)
        out_shape[out_shape.index(-1)] = total // known
    out = helper.create_variable_for_type_inference(x.dtype, tuple(out_shape))
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reshape2", {"X": x}, {"Out": out, "XShape": xshape},
                     {"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out_shape = tuple(x.shape[p] for p in perm) if x.shape else ()
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("transpose2", {"X": x}, {"Out": out, "XShape": xshape},
                     {"axis": list(perm)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    lead = _prod(x.shape[:axis]) if all(s >= 0 for s in x.shape[:axis]) else -1
    rest = _prod(x.shape[axis:]) if all(s >= 0 for s in x.shape[axis:]) else -1
    out = helper.create_variable_for_type_inference(x.dtype, (lead, rest))
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("flatten2", {"X": x}, {"Out": out, "XShape": xshape},
                     {"axis": axis})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    shape = tuple(s for i, s in enumerate(input.shape)
                  if not (i in [a % len(input.shape) for a in axes] and s == 1))
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    xshape = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("squeeze2", {"X": input}, {"Out": out, "XShape": xshape},
                     {"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    shape = list(input.shape)
    for a in sorted(axes):
        shape.insert(a, 1)
    out = helper.create_variable_for_type_inference(input.dtype, tuple(shape))
    xshape = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("unsqueeze2", {"X": input}, {"Out": out, "XShape": xshape},
                     {"axes": list(axes)})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    shape = list(xs[0].shape)
    shape.insert(axis % (len(shape) + 1), len(xs))
    out = helper.create_variable_for_type_inference(xs[0].dtype, tuple(shape))
    helper.append_op("stack", {"X": xs}, {"Y": out}, {"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    n = num or x.shape[axis]
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    outs = [helper.create_variable_for_type_inference(x.dtype, shape)
            for _ in range(n)]
    helper.append_op("unstack", {"X": x}, {"Y": outs}, {"axis": axis})
    return outs


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim % len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = None
        sizes = [input.shape[dim] // n] * n
    else:
        sections = list(num_or_sections)
        n = len(sections)
        sizes = sections
    outs = []
    for s in sizes:
        shape = list(input.shape)
        shape[dim] = s
        outs.append(helper.create_variable_for_type_inference(input.dtype, tuple(shape)))
    attrs = {"axis": dim}
    if sections:
        attrs["sections"] = sections
    else:
        attrs["num"] = n
    helper.append_op("split", {"X": input}, {"Out": outs}, attrs)
    return outs


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    xs = input if isinstance(input, (list, tuple)) else [input]
    shape = list(xs[0].shape)
    if shape:
        shape[axis % len(shape)] = builtins_sum(
            x.shape[axis % len(shape)] for x in xs)
    out = helper.create_variable_for_type_inference(xs[0].dtype, tuple(shape))
    helper.append_op("concat", {"X": xs}, {"Out": out}, {"axis": axis})
    return out


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    shape = list(input.shape)
    for a, s, e in zip(axes, starts, ends):
        dim = shape[a]
        if dim >= 0:
            s2 = max(s + dim, 0) if s < 0 else min(s, dim)
            e2 = max(e + dim, 0) if e < 0 else min(e, dim)
            shape[a] = max(e2 - s2, 0)
    out = helper.create_variable_for_type_inference(input.dtype, tuple(shape))
    helper.append_op("slice", {"Input": input}, {"Out": out},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    shape = (index.shape[0] if index.shape else -1,) + tuple(input.shape[1:])
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("gather", {"X": input, "Index": index}, {"Out": out})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", {"X": input, "Index": index}, {"Out": out})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("scatter", {"X": input, "Ids": index, "Updates": updates},
                     {"Out": out}, {"overwrite": overwrite})
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(ref.dtype, ref.shape)
    helper.append_op("scatter_nd_add",
                     {"X": ref, "Index": index, "Updates": updates},
                     {"Out": out})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    shape = tuple(s * t if s >= 0 else -1 for s, t in zip(x.shape, expand_times))
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("expand", {"X": x}, {"Out": out},
                     {"expand_times": list(expand_times)})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    shape = tuple(s + paddings[2 * i] + paddings[2 * i + 1] if s >= 0 else -1
                  for i, s in enumerate(x.shape))
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("pad", {"X": x}, {"Out": out},
                     {"paddings": list(paddings), "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", {"X": input}, {"Out": out},
                     {"paddings": list(paddings), "mode": mode,
                      "pad_value": float(pad_value), "data_format": data_format})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shape = tuple(input.shape[:-1]) + (k,)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    idx = helper.create_variable_for_type_inference("int64", shape)
    helper.append_op("top_k", {"X": input}, {"Out": out, "Indices": idx},
                     {"k": k})
    return out, idx


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    out = helper.create_variable_for_type_inference("int64", shape)
    helper.append_op("arg_max", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    out = helper.create_variable_for_type_inference("int64", shape)
    helper.append_op("arg_min", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    idx = helper.create_variable_for_type_inference("int64", input.shape)
    helper.append_op("argsort", {"X": input}, {"Out": out, "Indices": idx},
                     {"axis": axis, "descending": descending})
    return out, idx


def where(condition):
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("where", {"Condition": condition}, {"Out": out})
    return out


def sign(x):
    helper = LayerHelper("sign")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sign", {"X": x}, {"Out": out})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", (len(input.shape),))
    helper.append_op("shape", {"Input": input}, {"Out": out})
    return out


def rank(input):
    helper = LayerHelper("rank")
    out = helper.create_variable_for_type_inference("int32", ())
    helper.append_op("rank", {"Input": input}, {"Out": out})
    return out


def size(input):
    helper = LayerHelper("size")
    out = helper.create_variable_for_type_inference("int64", ())
    helper.append_op("size", {"Input": input}, {"Out": out})
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype, x.shape)
    helper.append_op("cast", {"X": x}, {"Out": out}, {"out_dtype": dtype})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("softmax", {"X": input}, {"Out": out}, {"axis": axis})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("relu", {"X": x}, {"Out": out})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("leaky_relu", {"X": x}, {"Out": out}, {"alpha": alpha})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=alpha_shape, dtype="float32",
        default_initializer=init_mod.ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("prelu", {"X": x, "Alpha": alpha}, {"Out": out},
                     {"mode": mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    shape = (x.shape[0], x.shape[1] // groups) + tuple(x.shape[2:])
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("maxout", {"X": x}, {"Out": out}, {"groups": groups})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mid = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("lrn", {"X": input}, {"Out": out, "MidOut": mid},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1):
    return _resize(input, out_shape, scale, "bilinear_interp", name,
                   align_corners=align_corners, align_mode=align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True):
    return _resize(input, out_shape, scale, "nearest_interp", name,
                   align_corners=align_corners)


def _resize(input, out_shape, scale, op_type, name, align_corners=True,
            align_mode=1):
    helper = LayerHelper(op_type, name=name)
    # the reference DEFAULTS to align_corners=True (nn.py:7861)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
        shape = tuple(input.shape[:2]) + (attrs["out_h"], attrs["out_w"])
    else:
        attrs["scale"] = float(scale)
        shape = tuple(input.shape[:2]) + tuple(
            int(s * scale) if s and s > 0 else -1 for s in input.shape[2:])
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op(op_type, {"X": input}, {"Out": out}, attrs)
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None,
                 align_corners=True, align_mode=1):
    """Parity: fluid.layers.image_resize (ref nn.py:7852): dispatch on
    resample; actual_shape (a runtime shape tensor) is unsupported —
    static shapes, pass out_shape."""
    if actual_shape is not None:
        raise NotImplementedError(
            "image_resize(actual_shape=...) needs runtime output shapes; "
            "pass a static out_shape (SURVEY §1 decision 4)")
    resample = resample.upper()
    if resample == "BILINEAR":
        return resize_bilinear(input, out_shape, scale, name,
                               align_corners, align_mode)
    if resample == "NEAREST":
        return resize_nearest(input, out_shape, scale, name, align_corners)
    if resample == "TRILINEAR":
        return resize_trilinear(input, out_shape, scale, name,
                                align_corners, align_mode)
    raise ValueError(
        "The 'resample' of image_resize can only be 'BILINEAR', "
        "'TRILINEAR' or 'NEAREST' currently.")


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    r = upscale_factor
    n, c, h, w = x.shape
    out = helper.create_variable_for_type_inference(
        x.dtype, (n, c // (r * r), h * r, w * r))
    helper.append_op("pixel_shuffle", {"X": x}, {"Out": out},
                     {"upscale_factor": r})
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Parity: fluid.layers.py_func — host callback via jax.pure_callback."""
    from ..core.framework import Operator
    helper = LayerHelper("py_func")
    func_id = len(Operator.CALLABLE_TABLE)
    Operator.CALLABLE_TABLE[func_id] = func
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper.append_op("py_func", {"X": xs}, {"Out": out}, {"func_id": func_id})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("add_position_encoding", {"X": input}, {"Out": out},
                     {"alpha": alpha, "beta": beta})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    out = helper.create_variable_for_type_inference(x.dtype, (x.shape[0], 1))
    diff = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("smooth_l1_loss", inputs, {"Out": out, "Diff": diff},
                     {"sigma": sigma or 1.0})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """Parity: fluid.layers.conv3d_transpose (conv_transpose_op.cc 3-D)."""
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    stride = _pair(stride, 3)
    padding = _pair(padding, 3)
    dilation = _pair(dilation, 3)
    num_channels = input.shape[1]
    fsize = _pair(filter_size, 3)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_channels, num_filters // (groups or 1)] + list(fsize),
        dtype=input.dtype,
        default_initializer=init_mod.XavierInitializer())
    spatial = [(input.shape[2 + i] - 1) * stride[i] - 2 * padding[i] +
               dilation[i] * (fsize[i] - 1) + 1 for i in range(3)]
    out_shape = (input.shape[0], num_filters) + tuple(spatial)
    pre_bias = helper.create_variable_for_type_inference(input.dtype, out_shape)
    helper.append_op("conv3d_transpose", {"Input": input, "Filter": w},
                     {"Output": pre_bias},
                     {"strides": stride, "paddings": padding,
                      "dilations": dilation, "groups": groups or 1})
    if helper.bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype, out_shape)
        helper.append_op("elementwise_add", {"X": pre_bias, "Y": b},
                         {"Out": pre_act}, {"axis": 1})
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False, slot_dim=-1,
              summary_decay_rate=0.9999999):
    """Parity: fluid.layers.data_norm (data_norm_op — CTR normalization by
    accumulated batch statistics, no learned scale)."""
    helper = LayerHelper("data_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    from ..core import unique_name as un
    size_name = un.generate(helper.name + ".batch_size")
    sum_name = un.generate(helper.name + ".batch_sum")
    sqs_name = un.generate(helper.name + ".batch_square_sum")
    bsize = helper.create_or_get_global_variable(size_name, shape=(c,),
                                                 dtype="float32",
                                                 persistable=True)
    bsum = helper.create_or_get_global_variable(sum_name, shape=(c,),
                                                dtype="float32",
                                                persistable=True)
    bsqs = helper.create_or_get_global_variable(sqs_name, shape=(c,),
                                                dtype="float32",
                                                persistable=True)
    init_mod.ConstantInitializer(1e4)(bsize)
    init_mod.ConstantInitializer(0.0)(bsum)
    init_mod.ConstantInitializer(1e4)(bsqs)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op(
        "data_norm",
        {"X": input, "BatchSize": bsize, "BatchSum": bsum,
         "BatchSquareSum": bsqs},
        {"Y": out, "BatchSizeOut": bsize, "BatchSumOut": bsum,
         "BatchSquareSumOut": bsqs},
        {"epsilon": epsilon, "summary_decay_rate": summary_decay_rate})
    return helper.append_activation(out) if act else out


def _simple_layer(op_type, ins, attrs, helper_name=None, out_slot="Out",
                  dtype=None, shape=None):
    helper = LayerHelper(helper_name or op_type)
    ref = next(iter(ins.values()))
    ref = ref[0] if isinstance(ref, (list, tuple)) else ref
    out = helper.create_variable_for_type_inference(
        dtype or ref.dtype, shape or getattr(ref, "shape", None))
    helper.append_op(op_type, ins, {out_slot: out}, attrs)
    return out


def moe(input, d_ff, num_experts, capacity_factor=1.25, param_attr=None,
        name=None):
    """TPU-native MoE FFN layer (expert parallelism — SURVEY §2.6 'ep').

    Top-1 (Switch) gated expert FFN over the last dim of `input`; on a
    mesh with an 'ep' axis the Executor runs the all_to_all dispatch
    path with expert weights sharded over 'ep' (their dist_attr is set
    here), otherwise all experts run locally. Returns (out, aux_loss) —
    add `aux_loss` (load-balance term) into the training loss.
    Extension beyond the reference surface: fluid 1.5 reaches scale via
    pserver sharded embeddings, which TPU re-expresses as conditional
    compute + ICI all_to_all (parallel/moe.py)."""
    import copy as _copy

    from ..core.param_attr import ParamAttr as _PA

    def _sub_attr(suffix):
        # one ParamAttr names THREE params here; suffix to keep them
        # distinct when the user passed an explicit name
        a = _PA._to_attr(param_attr)
        if a is None or a is False:
            return a
        a = _copy.copy(a)
        if a.name:
            a.name = a.name + suffix
        return a

    helper = LayerHelper("moe", name=name)
    d = int(input.shape[-1])
    gate_w = helper.create_parameter(
        attr=_sub_attr("_gate_w"), shape=[d, num_experts],
        dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, 0.02))
    w_up = helper.create_parameter(
        attr=_sub_attr("_w_up"), shape=[num_experts, d, d_ff],
        dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(
            0.0, (2.0 / d) ** 0.5))
    w_down = helper.create_parameter(
        attr=_sub_attr("_w_down"), shape=[num_experts, d_ff, d],
        dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(
            0.0, (2.0 / d_ff) ** 0.5))
    # expert-sharded placement over the 'ep' mesh axis (consumed by the
    # Executor's dist_attr path, like tp's shard rules)
    w_up.dist_attr = ("ep", None, None)
    w_down.dist_attr = ("ep", None, None)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    input.shape)
    aux = helper.create_variable_for_type_inference(input.dtype, (1,))
    helper.append_op("moe", {"X": input, "GateW": gate_w, "WUp": w_up,
                             "WDown": w_down},
                     {"Out": out, "AuxLoss": aux},
                     {"capacity_factor": capacity_factor})
    return out, aux


def grid_sampler(x, grid, name=None):
    """Parity: fluid.layers.grid_sampler (bilinear spatial sampling)."""
    return _simple_layer("grid_sampler", {"X": x, "Grid": grid}, {},
                         out_slot="Output")


def affine_grid(theta, out_shape, name=None):
    """Parity: fluid.layers.affine_grid."""
    helper = LayerHelper("affine_grid")
    if isinstance(out_shape, (list, tuple)):
        attrs = {"output_shape": [int(s) for s in out_shape]}
        ins = {"Theta": theta}
        shape = (out_shape[0], out_shape[2], out_shape[3], 2)
    else:
        attrs = {}
        ins = {"Theta": theta, "OutputShape": out_shape}
        shape = None
    out = helper.create_variable_for_type_inference(theta.dtype, shape)
    helper.append_op("affine_grid", ins, {"Output": out}, attrs)
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """Parity: fluid.layers.temporal_shift (TSM video models)."""
    return _simple_layer("temporal_shift", {"X": x},
                         {"seg_num": seg_num, "shift_ratio": shift_ratio})


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Parity: fluid.layers.row_conv (lookahead conv, DeepSpeech2)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[future_context_size + 1, d],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("row_conv", {"X": input, "Filter": w}, {"Out": out}, {})
    return helper.append_activation(out)


def multiplex(inputs, index):
    """Parity: fluid.layers.multiplex — per-row select among candidates."""
    return _simple_layer("multiplex", {"X": list(inputs), "Ids": index}, {},
                         helper_name="multiplex")


def crop(x, shape=None, offsets=None, name=None):
    """Parity: fluid.layers.crop / crop_tensor. `shape` must be a static
    list on TPU (XLA needs static slice sizes); `offsets` may be a tensor
    (dynamic_slice starts)."""
    from ..core.framework import Variable as _Var
    if isinstance(shape, _Var) or any(isinstance(s_, _Var)
                                      for s_ in (shape or [])):
        # Variable check (not hasattr-dtype: numpy scalars have .dtype
        # and are perfectly static)
        raise TypeError(
            "crop_tensor: tensor-valued `shape` (or shape element) is "
            "dynamic-shape; pass a python list of ints (use -1 to keep "
            "a dim)")
    ins = {"X": x}
    attrs = {"shape": list(shape), "offsets": offsets}
    if hasattr(offsets, "dtype"):
        ins["Offsets"] = offsets
        attrs["offsets"] = None
    # static out shape for downstream shape inference (fc sizes etc.):
    # -1/0 entries mean "rest of the dim from the offset"
    dynamic_offsets = not isinstance(offsets, (list, tuple)) \
        or any(isinstance(o, _Var) for o in offsets)
    if dynamic_offsets and any(int(s) <= 0 for s in shape):
        # the kernel rejects -1/0 sizes with runtime offsets; raise the
        # same contract here instead of publishing a bogus static shape
        raise NotImplementedError(
            "crop_tensor: -1/0 shape entries need static offsets "
            "(runtime offsets can't give a static slice size) — pass "
            "explicit sizes in `shape`, or make `offsets` a python list")
    off_list = offsets if not dynamic_offsets else [0] * len(shape)
    out_shape = tuple(
        int(s) if int(s) > 0
        else (int(x.shape[i]) - int(off_list[i])
              if int(x.shape[i]) >= 0 else -1)     # unknown dims stay -1
        for i, s in enumerate(shape))
    return _simple_layer("crop_tensor", ins, attrs, helper_name="crop",
                         shape=out_shape)


crop_tensor = crop


def space_to_depth(x, blocksize, name=None):
    return _simple_layer("space_to_depth", {"X": x}, {"blocksize": blocksize})


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    helper = LayerHelper("affine_channel", act=act)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("affine_channel",
                     {"X": x, "Scale": scale, "Bias": bias},
                     {"Out": out}, {"data_layout": data_layout})
    return helper.append_activation(out)


def similarity_focus(input, axis, indexes, name=None):
    return _simple_layer("similarity_focus", {"X": input},
                         {"axis": axis, "indexes": list(indexes)})


def fsp_matrix(x, y):
    """Parity: fluid.layers.fsp_matrix (distillation FSP Gram matrix)."""
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(
        x.dtype, (x.shape[0], x.shape[1], y.shape[1]))
    helper.append_op("fsp", {"X": x, "Y": y}, {"Out": out}, {})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """Parity: fluid.layers.im2sequence (ref nn.py:6375). Out rows are
    image windows ((N*oh*ow, C*kh*kw)); the uniform per-image step count
    rides the op's Length output (static shapes make the LoD uniform)."""
    helper = LayerHelper("im2sequence", name=name)
    ins = {"X": input}
    if input_image_size is not None:
        ins["Y"] = input_image_size        # op raises: dynamic windows
    out = helper.create_variable_for_type_inference(input.dtype)
    length = helper.create_variable_for_type_inference("int32")
    helper.append_op("im2sequence", ins, {"Out": out, "Length": length},
                     {"kernels": _pair(filter_size),
                      "strides": _pair(stride),
                      "paddings": _pair(padding, 4),
                      "out_stride": _pair(out_stride)})
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None, deformable_groups=None,
                    im2col_step=None, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """Parity: fluid.layers.deformable_conv (v1/v2)."""
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = input.shape[1]
    fsize = _pair(filter_size)
    w = helper.create_parameter(
        helper.param_attr, shape=[num_filters, c] + list(fsize),
        dtype=input.dtype, default_initializer=init_mod.XavierInitializer())
    ins = {"Input": input, "Offset": offset, "Filter": w}
    if modulated and mask is not None:
        ins["Mask"] = mask
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("deformable_conv", ins, {"Output": out},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation),
                      "deformable_groups": deformable_groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        out2 = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", {"X": out, "Y": b},
                         {"Out": out2}, {"axis": 1})
        return out2
    return out


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=[1, 1],
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    helper = LayerHelper("deformable_roi_pooling")
    ins = {"Input": input, "ROIs": rois}
    if not no_trans and trans is not None:
        ins["Trans"] = trans
    # reference layer (nn.py:13400-13405): PS mode divides the input
    # channels by the pooled area to get the output channel count.
    channels = input.shape[1]
    output_dim = channels // (pooled_height * pooled_width) \
        if position_sensitive else channels
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("deformable_psroi_pooling", ins, {"Output": out},
                     {"no_trans": no_trans,
                      "spatial_scale": spatial_scale,
                      "output_dim": output_dim,
                      "group_size": list(group_size),
                      "pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "part_size": list(part_size or [pooled_height, pooled_width]),
                      "sample_per_part": sample_per_part,
                      "trans_std": trans_std})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """Parity: fluid.layers.hash (sparse id hashing)."""
    return _simple_layer("hash", {"X": input},
                         {"num_hash": num_hash, "mod_by": hash_size},
                         helper_name="hash", dtype="int32")


def continuous_value_model(input, cvm, use_cvm=True):
    return _simple_layer("continuous_value_model", {"X": input, "CVM": cvm},
                         {"use_cvm": use_cvm}, helper_name="cvm",
                         out_slot="Y")


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True, out_val_if_empty=0):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype, ins.shape)
    loss_weight = helper.create_variable_for_type_inference("float32")
    index_map = helper.create_variable_for_type_inference("int32")
    helper.append_op("filter_by_instag",
                     {"Ins": ins, "Ins_tag": ins_tag,
                      "Filter_tag": filter_tag},
                     {"Out": out, "LossWeight": loss_weight,
                      "IndexMap": index_map}, {})
    return out, loss_weight, index_map


def shuffle_channel(x, group, name=None):
    """Parity: fluid.layers.shuffle_channel (ShuffleNet)."""
    return _simple_layer("shuffle_channel", {"X": x}, {"group": group})


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Parity: fluid.layers.adaptive_pool3d (NCDHW); require_index as in
    adaptive_pool2d (invalid with avg, returns (out, mask))."""
    ps = list(pool_size) if isinstance(pool_size, (list, tuple)) \
        else [pool_size] * 3
    return _adaptive_pool("adaptive_pool3d", input, ps, pool_type,
                          require_index, name)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1):
    """Parity: fluid.layers.resize_trilinear (NCDHW)."""
    helper = LayerHelper("trilinear_interp", name=name)
    if out_shape is not None:
        od, oh, ow = (int(s) for s in out_shape)
    else:
        od, oh, ow = (int(s * scale) for s in input.shape[2:])
    out = helper.create_variable_for_type_inference(
        input.dtype, tuple(input.shape[:2]) + (od, oh, ow))
    helper.append_op("trilinear_interp", {"X": input}, {"Out": out},
                     {"out_d": od, "out_h": oh, "out_w": ow,
                      "align_corners": align_corners,
                      "align_mode": align_mode})
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Parity: fluid.layers.image_resize_short — resize so the SHORT side
    equals out_short_len, keeping aspect ratio."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    oh = int(round(h * out_short_len / float(short)))
    ow = int(round(w * out_short_len / float(short)))
    if resample.upper() == "NEAREST":
        return resize_nearest(input, out_shape=[oh, ow])
    return resize_bilinear(input, out_shape=[oh, ow])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """Parity: fluid.layers.unfold (im2col): (N,C,H,W) -> (N, C*kh*kw, L).
    paddings may be 1, 2, or 4 ints ([top, left, bottom, right])."""
    helper = LayerHelper("unfold", name=name)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = list(paddings) if isinstance(paddings, (list, tuple)) \
        else [paddings, paddings]
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = _pair(dilations)
    n, c, h, w = x.shape
    oh = (h + pd[0] + pd[2] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
    ow = (w + pd[1] + pd[3] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
    out = helper.create_variable_for_type_inference(
        x.dtype, (n, c * ks[0] * ks[1], oh * ow))
    helper.append_op("unfold", {"X": x}, {"Y": out},
                     {"kernel_sizes": ks, "strides": st, "paddings": pd,
                      "dilations": dl})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """Parity: fluid.layers.bilinear_tensor_product:
    out[:, i] = x . W_i . y^T + b (one MXU einsum)."""
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         param_attr=param_attr, bias_attr=bias_attr, act=act)
    dx, dy = x.shape[-1], y.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[size, dx, dy], dtype=x.dtype)
    inputs = {"X": x, "Y": y, "Weight": w}
    b = helper.create_parameter(attr=helper.bias_attr, shape=[size],
                                dtype=x.dtype, is_bias=True)
    if b is not None:
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(
        x.dtype, (x.shape[0], size))
    helper.append_op("bilinear_tensor_product", inputs, {"Out": out}, {})
    return helper.append_activation(out)


def merge_selected_rows(x, name=None):
    """Parity shim: SelectedRows is re-designed away (dense grads via XLA
    scatter-add), so merging duplicate rows is the identity on device."""
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("merge_selected_rows", {"X": x}, {"Out": out}, {})
    return out


def get_tensor_from_selected_rows(x, name=None):
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("get_tensor_from_selected_rows", {"X": x},
                     {"Out": out}, {})
    return out


def lod_reset(x, y=None, target_lod=None):
    """Parity: fluid.layers.lod_reset. LoD lives host-side here
    (core/lod.py); on device this is the identity, the new lod is carried
    as metadata for layer-level consumers."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("lod_reset", {"X": x} | ({"Y": y} if y is not None
                                              else {}), {"Out": out},
                     {"target_lod": list(target_lod) if target_lod else []})
    out._lod_source = y if y is not None else target_lod
    return out


def lod_append(x, level):
    helper = LayerHelper("lod_append")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    inputs = {"X": x}
    if not isinstance(level, (list, tuple)):
        inputs["Y"] = level
    helper.append_op("lod_append", inputs, {"Out": out}, {})
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Parity: fluid.layers.linear_chain_crf (ref layers/nn.py:1409).
    Padded form: input (B, T, num_tags), label (B, T[, 1]), length (B,).
    Returns the per-sequence negative log-likelihood (B, 1) — the cost
    the reference feeds to the optimizer. The transition parameter is
    [num_tags + 2, num_tags]: rows 0/1 are start/end scores."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype="float32")
    b = input.shape[0]
    ll = helper.create_variable_for_type_inference("float32", (b, 1))
    alpha = helper.create_variable_for_type_inference("float32", input.shape)
    em_exps = helper.create_variable_for_type_inference("float32",
                                                        input.shape)
    tr_exps = helper.create_variable_for_type_inference(
        "float32", (num_tags + 2, num_tags))
    inputs = {"Emission": input, "Transition": transition, "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("linear_chain_crf", inputs,
                     {"LogLikelihood": ll, "Alpha": alpha,
                      "EmissionExps": em_exps, "TransitionExps": tr_exps},
                     {})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Parity: fluid.layers.crf_decoding — Viterbi path (B, T) int64
    (or per-position error flags when label is given). Pass the SAME
    param_attr name used by linear_chain_crf so decoding reads the
    trained transition."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype="float32")
    out = helper.create_variable_for_type_inference(
        "int64", tuple(input.shape[:2]))
    inputs = {"Emission": input, "Transition": transition}
    if label is not None:
        inputs["Label"] = label
    if length is not None:
        inputs["Length"] = length
    helper.append_op("crf_decoding", inputs, {"ViterbiPath": out}, {})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """Parity: fluid.layers.warpctc. Padded form: input (B, T, C) raw
    logits, label (B, L) padded. Returns per-sequence CTC loss (B, 1).
    (The reference's LoD form maps to the length args here —
    design decision 4.)"""
    helper = LayerHelper("warpctc")
    b = input.shape[0]
    loss = helper.create_variable_for_type_inference("float32", (b, 1))
    inputs = {"Logits": input, "Label": label}
    if input_length is not None:
        inputs["LogitsLength"] = input_length
    if label_length is not None:
        inputs["LabelLength"] = label_length
    helper.append_op("warpctc", inputs, {"Loss": loss},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Parity: fluid.layers.ctc_greedy_decoder — argmax, merge repeats,
    drop blanks. Returns (decoded (B, T) int64 padded with -1,
    lengths (B, 1)) — the padded replacement for the LoD output."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    b, t = input.shape[0], input.shape[1]
    out = helper.create_variable_for_type_inference("int64", (b, t))
    out_len = helper.create_variable_for_type_inference("int64", (b, 1))
    inputs = {"Input": input}
    if input_length is not None:
        inputs["InputLength"] = input_length
    helper.append_op("ctc_greedy_decoder", inputs,
                     {"Output": out, "OutputLength": out_len},
                     {"blank": blank})
    return out, out_len


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Parity: fluid.layers.nce (ref layers/nn.py:5955). Returns the
    per-row NCE cost (B, 1). is_sparse is moot on TPU (dense
    scatter-add grads)."""
    import numpy as np
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[num_total_classes], dtype=input.dtype,
                                is_bias=True)
    batch = input.shape[0]
    num_true = label.shape[1] if len(label.shape) > 1 else 1
    cost = helper.create_variable_for_type_inference("float32", (batch, 1))
    s_logits = helper.create_variable_for_type_inference(
        "float32", (batch, num_true + num_neg_samples))
    s_labels = helper.create_variable_for_type_inference(
        "int64", (batch, num_true + num_neg_samples))
    inputs = {"Input": input, "Label": label, "Weight": w}
    if b is not None:
        inputs["Bias"] = b
    if custom_dist is not None:
        from . import tensor as tensor_layers
        probs = tensor_layers.assign(
            np.asarray(custom_dist, np.float32))
        inputs["CustomDistProbs"] = probs
        sampler = "custom_dist"
    helper.append_op("nce", inputs,
                     {"Cost": cost, "SampleLogits": s_logits,
                      "SampleLabels": s_labels},
                     {"num_total_classes": num_total_classes,
                      "num_neg_samples": num_neg_samples,
                      "sampler": sampler, "op_seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Parity: fluid.layers.hsigmoid (ref layers/nn.py:6169), both the
    default complete-binary-tree form and custom trees via
    path_table/path_code (is_custom). Custom mode follows the reference
    weight shapes: W is [num_classes, dim] (one row per internal node id
    the path_table references), bias [num_classes, 1]."""
    if is_custom and (path_table is None or path_code is None
                      or num_classes is None):
        raise ValueError(
            "hsigmoid custom tree needs path_table, path_code and "
            "num_classes (ref layers/nn.py hsigmoid checks)")
    if not is_custom and (path_table is not None or path_code is not None):
        raise ValueError(
            "hsigmoid: path_table/path_code given without is_custom=True; "
            "pass is_custom=True to use the custom tree (W becomes "
            "[num_classes, dim])")
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    rows = num_classes if is_custom else num_classes - 1
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[rows, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[rows], dtype=input.dtype,
                                is_bias=True)
    batch = input.shape[0]
    if is_custom:
        max_depth = path_table.shape[-1]
    else:
        max_depth = max(int(num_classes - 1).bit_length(), 1)
    out = helper.create_variable_for_type_inference("float32", (batch, 1))
    pre = helper.create_variable_for_type_inference("float32",
                                                    (batch, max_depth))
    inputs = {"X": input, "W": w, "Label": label}
    if is_custom:
        inputs["PathTable"] = path_table
        inputs["PathCode"] = path_code
    if b is not None:
        inputs["Bias"] = b
    helper.append_op("hierarchical_sigmoid", inputs,
                     {"Out": out, "PreOut": pre},
                     {"num_classes": num_classes})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Parity: fluid.layers.sampled_softmax_with_cross_entropy
    (ref layers/nn.py:6748): softmax CE over {true + log-uniform sampled}
    classes with logQ correction. Returns loss (B, 1)."""
    if use_customized_samples:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: customized samples are "
            "not supported; the op draws its own log-uniform samples")
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    batch = logits.shape[0]
    loss = helper.create_variable_for_type_inference("float32", (batch, 1))
    samples = helper.create_variable_for_type_inference(
        "int64", (batch, num_true + num_samples))
    s_logits = helper.create_variable_for_type_inference(
        "float32", (batch, num_true + num_samples))
    helper.append_op("sample_logits", {"Logits": logits, "Labels": label},
                     {"Loss": loss, "Samples": samples,
                      "SampledLogits": s_logits},
                     {"num_samples": num_samples,
                      "remove_accidental_hits": remove_accidental_hits,
                      "use_customized_samples": use_customized_samples,
                      "op_seed": seed})
    return loss


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None,
                        x_length=None, y_length=None):
    """Parity: fluid.layers.match_matrix_tensor — bilinear match matrix
    out[b, c, i, j] = x_bi . W_c . y_bj. Padded form: x (B, Lx, D),
    y (B, Ly, D) (+ optional lengths). Returns (out (B, C, Lx, Ly), tmp)."""
    helper = LayerHelper("match_matrix_tensor", param_attr=param_attr,
                         act=act, name=name)
    d = x.shape[-1]
    dy = y.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[d, channel_num, dy], dtype=dtype)
    b, lx = x.shape[0], x.shape[1]
    ly = y.shape[1]
    out = helper.create_variable_for_type_inference(
        dtype, (b, channel_num, lx, ly))
    tmp = helper.create_variable_for_type_inference(
        dtype, (b, lx, channel_num, dy))
    inputs = {"X": x, "Y": y, "W": w}
    if x_length is not None:
        inputs["XLength"] = x_length
    if y_length is not None:
        inputs["YLength"] = y_length
    helper.append_op("match_matrix_tensor", inputs,
                     {"Out": out, "Tmp": tmp}, {})
    return helper.append_activation(out), tmp


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32",
                name=None):
    """Parity: fluid.layers.var_conv_2d — conv over per-row variable-size
    images; padded form masks outputs beyond each row's (row, col) valid
    extent."""
    helper = LayerHelper("var_conv_2d", param_attr=param_attr, act=act,
                         name=name)
    ks = _pair(filter_size)
    st = _pair(stride)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[output_channel, input_channel, ks[0], ks[1]], dtype=dtype)
    b, _, h, wd = input.shape
    out = helper.create_variable_for_type_inference(
        dtype, (b, output_channel,
                (h + st[0] - 1) // st[0], (wd + st[1] - 1) // st[1]))
    inputs = {"X": input, "W": w}
    if row is not None:
        inputs["Row"] = row
    if col is not None:
        inputs["Col"] = col
    helper.append_op("var_conv_2d", inputs, {"Out": out},
                     {"strides": list(st)})
    return helper.append_activation(out)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Parity: fluid.layers.tree_conv (TBCNN, any max_depth).
    nodes_vector (B, N, D) with node ids 1-based (row id-1 is the
    feature); edge_set (B, E, 2) (parent, child) int pairs padded with
    0, the reference's convention (math/tree2col.cc:72).
    Returns (B, N, output_size, num_filters)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    d = nodes_vector.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[d, 3, output_size, num_filters],
                                dtype=nodes_vector.dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[output_size, num_filters],
                                   dtype=nodes_vector.dtype, is_bias=True)
    b, n = nodes_vector.shape[0], nodes_vector.shape[1]
    out = helper.create_variable_for_type_inference(
        nodes_vector.dtype, (b, n, output_size, num_filters))
    inputs = {"NodesVector": nodes_vector, "EdgeSet": edge_set,
              "Filter": w}
    if bias is not None:
        inputs["Bias"] = bias
    helper.append_op("tree_conv", inputs, {"Out": out},
                     {"max_depth": max_depth})
    return helper.append_activation(out)
