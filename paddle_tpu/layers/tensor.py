"""Tensor creation/manipulation layers.

Parity: python/paddle/fluid/layers/tensor.py.
"""

import numpy as np

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from .. import initializer as init_mod
from .nn import (cast, concat, argmin, argmax, argsort)  # re-export parity


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.param_attr import ParamAttr
    if attr is None and name is not None:
        attr = ParamAttr(name=name)
    helper = LayerHelper("create_parameter", param_attr=attr, name=name)
    if default_initializer is None:
        default_initializer = (init_mod.ConstantInitializer(0.0) if is_bias
                               else init_mod.XavierInitializer())
    return helper.create_parameter(helper.param_attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        persistable=persistable, name=name, shape=shape, dtype=dtype)
    init_mod.ConstantInitializer(value)(var)
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("fill_constant", {}, {"Out": out},
                     {"shape": list(shape), "dtype": dtype,
                      "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("fill_constant_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "dtype": dtype,
                      "value": float(value), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(
                input.dtype, input.shape)
        helper.append_op("assign", {"X": input}, {"Out": output})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(arr.dtype), arr.shape)
        helper.append_op("assign_value", {}, {"Out": output},
                         {"shape": list(arr.shape), "dtype": str(arr.dtype),
                          "values": arr.reshape(-1).tolist()})
    return output


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("zeros_like", {"X": x}, {"Out": out})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("ones_like", {"X": x}, {"Out": out}, {"value": 1.0})
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    xs = input if isinstance(input, (list, tuple)) else [input]
    if out is None:
        out = helper.create_variable_for_type_inference(xs[0].dtype, xs[0].shape)
    helper.append_op("sum", {"X": xs}, {"Out": out})
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(dtype, (num,))
    helper.append_op("linspace", {}, {"Out": out},
                     {"start": float(start), "stop": float(stop),
                      "num": int(num), "dtype": dtype})
    return out


def range(start, end, step, dtype="float32"):
    helper = LayerHelper("range")
    n = int(max(0, np.ceil((end - start) / step)))
    out = helper.create_variable_for_type_inference(dtype, (n,))
    helper.append_op("range", {}, {"Out": out},
                     {"start": start, "end": end, "step": step, "dtype": dtype})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    num_columns = num_columns if num_columns is not None else num_rows
    out = helper.create_variable_for_type_inference(dtype, (num_rows, num_columns))
    helper.append_op("eye", {}, {"Out": out},
                     {"num_rows": num_rows, "num_columns": num_columns,
                      "dtype": dtype})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    n = diagonal.shape[0] if diagonal.shape else -1
    out = helper.create_variable_for_type_inference(diagonal.dtype, (n, n))
    helper.append_op("diag", {"Diagonal": diagonal}, {"Out": out})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    helper.append_op("reverse", {"X": x}, {"Out": out}, {"axis": list(axes)})
    return out


def has_inf(x):
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference("bool", ())
    helper.append_op("has_inf", {"X": x}, {"Out": out})
    return out


def has_nan(x):
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference("bool", ())
    helper.append_op("has_nan", {"X": x}, {"Out": out})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool", ())
    helper.append_op("isfinite", {"X": x}, {"Out": out})
    return out


def scatter_nd(index, updates, shape, name=None):
    """Parity: fluid.layers.scatter_nd."""
    helper = LayerHelper("scatter_nd", name=name)
    out = helper.create_variable_for_type_inference(updates.dtype, tuple(shape))
    helper.append_op("scatter_nd", {"Index": index, "Updates": updates},
                     {"Out": out}, {"shape": list(shape)})
    return out


def strided_slice(input, axes, starts, ends, strides, name=None):
    """Parity: fluid.layers.strided_slice."""
    helper = LayerHelper("strided_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("strided_slice", {"Input": input}, {"Out": out},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends), "strides": list(strides)})
    return out


def unique(x, dtype="int32"):
    """Parity: fluid.layers.unique. Static-shape variant: returns (out,
    index) where out is padded to x.size with the first element (jnp.unique
    size= semantics — TPU needs static shapes)."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    index = helper.create_variable_for_type_inference(dtype, x.shape)
    helper.append_op("unique", {"X": x}, {"Out": out, "Index": index},
                     {"dtype": dtype})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    index = helper.create_variable_for_type_inference(dtype, x.shape)
    count = helper.create_variable_for_type_inference(dtype, x.shape)
    helper.append_op("unique_with_counts", {"X": x},
                     {"Out": out, "Index": index, "Count": count},
                     {"dtype": dtype})
    return out, index, count


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Parity: fluid.layers.shard_index (sharded embedding ids)."""
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("shard_index", {"X": input}, {"Out": out},
                     {"index_num": index_num, "nshards": nshards,
                      "shard_id": shard_id, "ignore_value": ignore_value})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Parity: fluid.layers.pad_constant_like — pad y to x's shape."""
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype, x.shape)
    helper.append_op("pad_constant_like", {"X": x, "Y": y}, {"Out": out},
                     {"pad_value": float(pad_value)})
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Parity: fluid.layers.tensor_array_to_tensor — concat (or stack) a
    TensorArray along `axis`. Returns (out, index) like the reference,
    where index holds each entry's size along `axis`."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    index = helper.create_variable_for_type_inference("int32")
    helper.append_op("tensor_array_to_tensor", {"X": input},
                     {"Out": out}, {"axis": axis, "use_stack": use_stack})
    helper.append_op("tensor_array_sizes", {"X": input}, {"Out": index},
                     {"axis": axis})
    return out, index
