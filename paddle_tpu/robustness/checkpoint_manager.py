"""Last-K-good checkpoint retention with retry/backoff and validated,
fall-back restore.

Layout (one retention root per training run):

    root/
      ckpt-00000040/ {state.npz, meta.json}     # meta.json commits it
      ckpt-00000080/ ...

Every save goes through io.checkpoint's atomic write sequence (temp +
fsync + os.replace, manifest with per-array CRC32, meta.json last), so
a directory without a valid meta.json is by construction an aborted
save, never a torn-but-loadable state. Restores walk newest-to-oldest
past corrupt/incomplete candidates. Write errors retry with bounded
deterministic exponential backoff (`sleep_fn` injectable so the chaos
tier never sleeps for real), then surface.
"""

import glob
import os
import shutil
import time

from ..core.framework import default_main_program
from ..observability import ComponentStats
from ..io.checkpoint import (CheckpointCorruptError, load_checkpoint,
                             save_checkpoint)

__all__ = ["CheckpointManager", "CheckpointError"]


class CheckpointError(RuntimeError):
    """A checkpoint write failed after exhausting its retry budget
    (carries the last underlying error as __cause__)."""


class CheckpointManager:
    """Keep the last `keep` good checkpoints under `root`.

    save()/restore() capture and restore the EXECUTOR's step counter
    too (meta.extra["exe_step_counter"]): the in-graph RNG folds that
    counter in, so a rollback that skipped restoring it would replay
    steps with different randomness and break bitwise recovery.
    """

    def __init__(self, root, keep=3, program=None, retries=3,
                 backoff_s=0.1, backoff_factor=2.0, sleep_fn=None):
        self.root = str(root)
        self.keep = max(1, int(keep))
        self.program = program
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.sleep_fn = sleep_fn if sleep_fn is not None else time.sleep
        self._stats = ComponentStats(
            gauge_labels={"manager": os.path.basename(self.root) or "ckpt"})

    # -- listing -------------------------------------------------------
    def _dir_for(self, step):
        return os.path.join(self.root, f"ckpt-{int(step):08d}")

    def checkpoints(self):
        """Committed checkpoint dirs, oldest first (commit marker =
        meta.json present)."""
        out = []
        for d in sorted(glob.glob(os.path.join(self.root, "ckpt-*"))):
            if os.path.exists(os.path.join(d, "meta.json")):
                out.append(d)
        return out

    def latest(self):
        ck = self.checkpoints()
        return ck[-1] if ck else None

    # -- save ----------------------------------------------------------
    def save(self, executor, step, scope=None, extra=None):
        """Atomically commit a checkpoint for `step`, with bounded
        retry + exponential backoff on I/O errors; prunes to `keep`
        afterwards. Returns the committed directory."""
        meta_extra = dict(extra or {})
        meta_extra.setdefault("exe_step_counter",
                              int(getattr(executor, "_step_counter", 0)))
        dirname = self._dir_for(step)
        delay = self.backoff_s
        last_err = None
        for attempt in range(self.retries + 1):
            try:
                save_checkpoint(executor, dirname,
                                main_program=self.program, step=step,
                                extra=meta_extra, scope=scope)
                break
            except OSError as e:
                last_err = e
                self._stats.count("checkpoint.write_failures")
                if attempt == self.retries:
                    raise CheckpointError(
                        f"checkpoint write for step {step} failed after "
                        f"{self.retries + 1} attempts: {e}") from e
                self.sleep_fn(delay)
                delay *= self.backoff_factor
        self._prune()
        return dirname

    def _prune(self):
        ck = self.checkpoints()
        while len(ck) > self.keep:
            victim = ck.pop(0)
            shutil.rmtree(victim, ignore_errors=True)
            self._stats.count("checkpoint.evictions")
        self._stats.set_gauge("checkpoint.retained", len(ck))

    # -- restore -------------------------------------------------------
    def restore(self, executor, scope=None, restore_step_counter=True):
        """Load the newest VALID checkpoint (walking back past corrupt
        or incomplete ones, counting fallbacks) into `scope` and — by
        default — wind the executor's step counter back to the saved
        value so replayed steps reuse their original RNG folds.
        Returns the meta dict, or None when the root holds nothing."""
        candidates = self.checkpoints()
        if not candidates:
            return None
        last_err = None
        for dirname in reversed(candidates):
            try:
                meta = load_checkpoint(executor, dirname,
                                       main_program=self.program,
                                       scope=scope)
            except (OSError, ValueError, CheckpointCorruptError) as e:
                last_err = e
                self._stats.count("checkpoint.fallbacks")
                continue
            if restore_step_counter and hasattr(executor, "_step_counter"):
                counter = meta.get("extra", {}).get("exe_step_counter")
                if counter is not None:
                    executor._step_counter = int(counter)
            meta["dir"] = dirname
            return meta
        raise CheckpointCorruptError(
            f"{self.root}: no loadable checkpoint among "
            f"{len(candidates)} candidates") from last_err
