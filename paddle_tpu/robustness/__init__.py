"""Fault-tolerant training: sentinels, rollback, preemption, chaos.

The whole-Program trace+jit Executor (core/executor.py) makes a step
cheap; this layer makes a RUN survivable. Four pieces, wired through
the Executor and checkpoint I/O (docs/robustness.md):

- guard.py    — NaN/Inf sentinels folded into the compiled step
                (`Executor(guard=True)` / PADDLE_TPU_GUARD=1); a
                tripped sentinel raises NonFiniteError where results
                are observed, naming the first bad var and step.
- checkpoint_manager.py — last-K-good retention over io.checkpoint's
                atomic (temp + fsync + os.replace, CRC32 manifest)
                write path, with retry/backoff and fall-back restore.
- trainer.py  — GuardedTrainer: checkpoint-segmented training loop
                that rolls back, runs recovery hooks (LR backoff, AMP
                loss-scale reduction), replays, and bounds retries.
- preemption.py / chaos.py — SIGTERM/SIGINT drain-and-save, and the
                deterministic fault injector the chaos test tier uses
                to exercise every recovery path without flaky timing.
- supervisor.py — the SERVING side of the same story: the fleet
                watchdog (hung-replica detection off progress marks),
                replica resurrection under a crash-loop circuit
                breaker with checkpoint weight reload and prefix-cache
                re-warm, and the poison-request quarantine error
                (docs/robustness.md "Self-healing fleet").
"""

from .guard import GuardConfig, NonFiniteError
from .chaos import ChaosInjector, CheckpointWriteFault
from .checkpoint_manager import CheckpointError, CheckpointManager
from .preemption import PreemptionHandler
from .supervisor import (ChunkPopularityDigest, FleetSupervisor,
                         PoisonRequestError, SupervisorConfig,
                         make_checkpoint_spawn)
from .trainer import (GuardedTrainer, RecoveryPolicy, TrainResult,
                      lr_backoff)

__all__ = [
    "GuardConfig", "NonFiniteError",
    "ChaosInjector", "CheckpointWriteFault",
    "CheckpointError", "CheckpointManager",
    "PreemptionHandler",
    "FleetSupervisor", "SupervisorConfig", "PoisonRequestError",
    "ChunkPopularityDigest", "make_checkpoint_spawn",
    "GuardedTrainer", "RecoveryPolicy", "TrainResult", "lr_backoff",
]
