"""GuardedTrainer: the fault-tolerant training loop.

Drives feeds through the Executor's async window in CHECKPOINT SEGMENTS:
dispatch up to `checkpoint_every` steps (window-pipelined), drain, commit
an atomic checkpoint, repeat. Dispatch never crosses an uncommitted
segment boundary, so a checkpoint always captures exactly the state of
the steps it claims (the in-flight window would otherwise have advanced
the scope past the step being saved).

Fault handling:
- a NonFiniteError surfacing from any handle rolls the scope back to the
  last good checkpoint (CheckpointManager walks past corrupt ones),
  rewinds the executor's RNG step counter, runs the recovery hooks
  (LR backoff / AMP loss-scale reduction), and REPLAYS the segment's
  buffered feeds — bounded by `RecoveryPolicy.max_retries` rollbacks per
  segment, then the fault surfaces;
- checkpoint I/O errors retry with exponential backoff inside the
  manager, then surface as CheckpointError;
- a preemption request (SIGTERM/SIGINT via PreemptionHandler, or the
  chaos injector) drains the window, writes an emergency checkpoint
  through the same atomic path, and returns cleanly with
  `result.preempted`.

Step accounting (docs/robustness.md): steps since the last committed
checkpoint are replayed after a rollback, so `result_callback` delivery
is at-least-once within a segment; checkpoint commits are exactly-once.
"""

import collections
import os
import warnings

import numpy as np

from ..observability import ComponentStats
from .chaos import CheckpointWriteFault  # noqa: F401  (re-export surface)
from .checkpoint_manager import CheckpointError, CheckpointManager
from .guard import NonFiniteError

__all__ = ["GuardedTrainer", "RecoveryPolicy", "TrainResult",
           "lr_backoff"]


def lr_backoff(optimizer_or_name, factor=0.5):
    """Recovery hook: multiply the live learning-rate scope var by
    `factor` on every rollback (the classic divergence response). Takes
    an optimizer (its `_lr_var`) or the LR var name."""

    def hook(scope, fault):
        import jax.numpy as jnp
        name = optimizer_or_name
        if not isinstance(name, str):
            lr_var = getattr(optimizer_or_name, "_lr_var", None)
            if lr_var is None:
                return
            name = lr_var.name
        val = scope.get(name)
        if val is not None:
            scope.set(name, jnp.asarray(np.asarray(val) * factor))
    return hook


class RecoveryPolicy:
    """What happens after the sentinel trips.

    max_retries    — rollbacks allowed per checkpoint segment before the
                     fault surfaces to the caller;
    skip_bad_batch — drop the offending feed on rollback instead of
                     retrying it (persistent-poison response; the
                     default retries, which heals transient faults);
    on_rollback    — callables `hook(scope, fault)` run after each
                     restore (e.g. `lr_backoff(...)`,
                     `amp_optimizer.rollback_hook()`).
    """

    def __init__(self, max_retries=2, skip_bad_batch=False,
                 on_rollback=()):
        self.max_retries = max(0, int(max_retries))
        self.skip_bad_batch = bool(skip_bad_batch)
        self.on_rollback = list(on_rollback)


class TrainResult:
    """What a GuardedTrainer.train() call did."""

    def __init__(self):
        self.steps = 0               # optimizer steps committed/resolved
        self.rollbacks = 0
        self.skipped = []            # step indices dropped by the policy
        self.faults = []             # NonFiniteErrors recovered from
        self.preempted = False
        self.emergency_dir = None
        self.checkpoints_written = 0
        self.flight_dumps = []       # flight-<step>.json paths written

    def __repr__(self):
        return (f"TrainResult(steps={self.steps}, "
                f"rollbacks={self.rollbacks}, "
                f"preempted={self.preempted})")


class GuardedTrainer:
    """Fault-tolerant loop over (executor, program, feeds).

    The executor should be constructed with `guard=True` (or
    PADDLE_TPU_GUARD=1): without the in-step sentinel only hard device
    errors trigger rollback and divergence sails into the checkpoints.
    """

    def __init__(self, executor, program, fetch_list=None, scope=None,
                 checkpoint_dir=None, manager=None, checkpoint_every=100,
                 policy=None, chaos=None, preemption=None, window=None,
                 result_callback=None, final_checkpoint=True,
                 flight=True):
        from ..core.executor import global_scope
        if manager is None:
            if checkpoint_dir is None:
                raise ValueError(
                    "GuardedTrainer needs a checkpoint_dir or a "
                    "CheckpointManager — rollback has to restore from "
                    "somewhere")
            manager = CheckpointManager(checkpoint_dir, program=program)
        if manager.program is None:
            manager.program = program
        self.exe = executor
        self.program = program
        self.fetch_list = list(fetch_list or [])
        self.scope = scope if scope is not None else global_scope()
        self.manager = manager
        self.every = max(1, int(checkpoint_every))
        self.policy = policy if policy is not None else RecoveryPolicy()
        self.chaos = chaos
        self.preemption = preemption
        self.window = max(1, int(window if window is not None
                                 else executor.async_window))
        self.result_callback = result_callback
        self.final_checkpoint = bool(final_checkpoint)
        self._step = 0              # committed+resolved optimizer steps
        self._resumed_from = None   # dir resume() restored, if any
        self._stats = ComponentStats()
        # fault flight recorder (observability/serving_telemetry.py):
        # a small ring of recent dispatch/resolve events, dumped as
        # flight-<step>.json into the checkpoint root when the NaN/Inf
        # sentinel trips — the postmortem answers "what was in flight
        # when it went bad" without re-running. flight=False disables;
        # a FlightRecorder instance redirects (e.g. a shared ring).
        if flight is True:
            from ..observability.serving_telemetry import FlightRecorder
            flight = FlightRecorder(capacity=64, out_dir=self.manager.root)
        elif flight is False:
            flight = None
        self.flight = flight
        if getattr(executor, "_guard", None) is None:
            warnings.warn(
                "GuardedTrainer wraps an executor without the NaN/Inf "
                "guard — pass Executor(guard=True) or set "
                "PADDLE_TPU_GUARD=1, or rollback only fires on hard "
                "device errors", stacklevel=2)

    @property
    def step(self):
        return self._step

    def resume(self):
        """Restore the newest valid checkpoint (if any) and continue
        from its step. Returns the checkpoint meta or None."""
        meta = self.manager.restore(self.exe, scope=self.scope)
        if meta is not None:
            self._step = int(meta.get("step", 0))
            self._resumed_from = meta.get("dir")
        return meta

    # ------------------------------------------------------------------
    def train(self, feeds, num_steps=None):
        it = iter(feeds)
        res = TrainResult()
        pending = collections.deque()    # (idx, FetchHandle), FIFO
        buffer = {}                      # idx -> ORIGINAL feed (replay)
        replay = collections.deque()     # idxs to re-dispatch
        exhausted = False
        preempt = False
        segment_rollbacks = 0
        last_ckpt = self._step
        dispatch_idx = self._step        # next FRESH feed's index
        target = None if num_steps is None \
            else self._step + int(num_steps)

        # a rollback target matching THIS run's current state must exist
        # before the first step runs. Checking `latest() is None` is not
        # enough: a reused retention root from a previous run would make
        # the first rollback restore THAT run's weights and step count —
        # refuse the ambiguous case instead of silently training into it.
        latest = self.manager.latest()
        want = self.manager._dir_for(self._step)
        if latest is not None and \
                os.path.basename(latest) > os.path.basename(want):
            raise RuntimeError(
                f"checkpoint root {self.manager.root!r} already holds "
                f"{os.path.basename(latest)}, newer than this trainer's "
                f"step {self._step} — call resume() to continue from it, "
                f"or point the trainer at a fresh checkpoint_dir")
        if latest != want or latest != self._resumed_from:
            # also OVERWRITES an equal-step checkpoint we did NOT just
            # resume from (a dead previous run's baseline): the rollback
            # target must hold THIS run's live state, not foreign
            # weights that happen to share a step number
            self.manager.save(self.exe, self._step, scope=self.scope)
            res.checkpoints_written += 1

        def save_checkpoint(extra=None):
            nonlocal last_ckpt, segment_rollbacks
            d = self.manager.save(self.exe, self._step, scope=self.scope,
                                  extra=extra)
            res.checkpoints_written += 1
            last_ckpt = self._step
            segment_rollbacks = 0
            for i in [i for i in buffer if i < last_ckpt]:
                buffer.pop(i)
            return d

        def resolve_oldest():
            """Resolve the oldest in-flight step; on a sentinel trip,
            roll back. Any other error propagates."""
            idx, h = pending.popleft()
            try:
                out = h.result()
            except NonFiniteError as e:
                rollback(e, idx)
                return
            self._step = idx + 1
            if self.flight is not None:
                self.flight.record(idx, kind="resolve",
                                   committed_step=self._step,
                                   inflight=len(pending))
            if self.result_callback is not None:
                self.result_callback(idx, out)

        def rollback(fault, fault_idx):
            nonlocal segment_rollbacks, dispatch_idx, target, last_ckpt
            res.faults.append(fault)
            segment_rollbacks += 1
            if self.flight is not None:
                # dump BEFORE restore mutates anything: the ring's last
                # entry is the fault itself, identifying the offending
                # step, the segment base, and the in-flight window
                self.flight.record(
                    fault_idx, kind="fault", var=fault.var,
                    fault_step=fault.step, bad_vars=list(fault.bad_vars),
                    segment_base=last_ckpt,
                    rollbacks_this_segment=segment_rollbacks,
                    inflight=len(pending))
                res.flight_dumps.append(self.flight.dump(
                    "nonfinite_rollback", step=fault_idx,
                    extra={"var": fault.var, "step": fault.step,
                           "bad_vars": list(fault.bad_vars),
                           "segment_base": last_ckpt,
                           "rollbacks_this_segment": segment_rollbacks,
                           "will_surface": segment_rollbacks
                           > self.policy.max_retries}))
            # later in-flight steps ran on poisoned state: retire them
            while pending:
                _i, h = pending.popleft()
                try:
                    h.wait()
                except Exception:
                    pass
            if segment_rollbacks > self.policy.max_retries:
                raise fault              # retry budget spent: surface
            meta = self.manager.restore(self.exe, scope=self.scope)
            if meta is None:
                raise fault
            res.rollbacks += 1
            self._stats.count("executor.fault.rollbacks")
            self._step = int(meta.get("step", 0))
            if self._step < last_ckpt:
                # restore() fell back PAST the segment base (the latest
                # checkpoint was corrupt): the feeds for steps
                # [restored, last_ckpt) were pruned when that checkpoint
                # committed and cannot be replayed — say so loudly and
                # rebase the segment instead of silently mis-counting
                warnings.warn(
                    f"rollback fell back to step {self._step}, past the "
                    f"segment base {last_ckpt}; feeds for steps "
                    f"{self._step}..{last_ckpt - 1} were already "
                    f"consumed and are LOST to the resumed run",
                    stacklevel=2)
                last_ckpt = self._step
            # the restore just UNDID every earlier retry's hook effect
            # (LR, loss scale live in the checkpointed state): compound
            # the hooks once per rollback this segment, so retry n runs
            # at factor**n — otherwise a deterministic fault replays
            # bitwise-identically and extra retries are wasted work
            for _ in range(segment_rollbacks):
                for hook in self.policy.on_rollback:
                    hook(self.scope, fault)
            if self.policy.skip_bad_batch and fault_idx in buffer:
                # drop the offending feed; later buffered feeds (and
                # the not-yet-consumed stream) shift down one index
                res.skipped.append(fault_idx)
                self._stats.count("executor.fault.skipped_batches")
                tail = [buffer.pop(i) for i in sorted(buffer)
                        if i > fault_idx]
                buffer.pop(fault_idx, None)
                for j, f in enumerate(tail):
                    buffer[fault_idx + j] = f
                dispatch_idx -= 1
                if target is not None:
                    target -= 1
            replay.clear()
            replay.extend(i for i in sorted(buffer) if i >= self._step)

        def next_dispatch():
            """-> (idx, feed) or None (boundary / stream end / target)."""
            nonlocal dispatch_idx, exhausted
            if replay:
                # replays are never barrier-blocked: they were admitted
                # into a previous segment attempt, and after a fallback
                # restore their indices can legitimately sit past the
                # rebased boundary (the next checkpoint just covers a
                # longer segment)
                idx = replay.popleft()
                return idx, buffer[idx]
            idx = dispatch_idx
            if idx >= last_ckpt + self.every:
                return None                  # checkpoint barrier
            if target is not None and idx >= target:
                return None
            if exhausted:
                return None
            try:
                f = next(it)
            except StopIteration:
                exhausted = True
                return None
            buffer[idx] = f
            dispatch_idx = idx + 1
            return idx, f

        while True:
            if self.preemption is not None and self.preemption.requested():
                preempt = True
            if preempt:
                break
            # fill the window up to the segment boundary
            while len(pending) < self.window:
                was_replay = bool(replay)
                nidx = replay[0] if replay else dispatch_idx
                if self.chaos is not None \
                        and self.chaos.should_preempt(nidx):
                    preempt = True
                    break
                nd = next_dispatch()
                if nd is None:
                    break
                idx, feed = nd
                if self.chaos is not None:
                    feed = self.chaos.on_dispatch(idx, feed)
                h = self.exe.run_async(self.program, feed=feed,
                                       fetch_list=self.fetch_list,
                                       scope=self.scope,
                                       window=self.window)
                pending.append((idx, h))
                if self.flight is not None:
                    self.flight.record(idx, kind="dispatch",
                                       inflight=len(pending),
                                       segment_base=last_ckpt,
                                       replay=was_replay)
            if preempt:
                continue
            if pending:
                resolve_oldest()
                continue
            # window empty: segment boundary, target, or stream end
            if self._step > last_ckpt \
                    and self._step - last_ckpt >= self.every:
                save_checkpoint()
                continue
            break                            # stream/target exhausted

        if preempt:
            # drain what is in flight (a fault here still rolls back),
            # then commit the emergency checkpoint atomically
            while pending:
                resolve_oldest()
            res.preempted = True
            self._stats.count("executor.fault.preemptions")
            res.emergency_dir = save_checkpoint(extra={"emergency": True})
            self._stats.count("checkpoint.emergency_saves")
            if self.preemption is not None:
                self.preemption.clear()
        elif self.final_checkpoint and self._step > last_ckpt:
            save_checkpoint()

        res.steps = self._step
        return res
