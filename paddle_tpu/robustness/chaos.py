"""Deterministic fault injection for the recovery paths.

Every fault the robustness layer recovers from can be reproduced here
WITHOUT flaky timing: a grad poisoned at exactly step k (by NaN-ing a
float feed entry before dispatch, so the real sentinel trips on real
arithmetic), the Nth physical checkpoint write failing (via the
io.checkpoint write-fault hook, so the torn-file handling is exercised
on the real write path), and a SIGTERM delivered at step k (routed
through the same PreemptionHandler flag a real signal sets). The
injector is seedable only for *choosing* targets — firing is always an
exact step/write count, never a probability, so the chaos tier stays
deterministic (pytest marker `chaos`, tier-1).
"""

import numpy as np

__all__ = ["ChaosInjector", "CheckpointWriteFault"]


class CheckpointWriteFault(OSError):
    """The injected checkpoint I/O failure (an OSError subclass, so the
    retry/backoff path treats it exactly like a real disk error)."""


class ChaosInjector:
    """One injector instance = one fault plan, consumed by a
    GuardedTrainer (`chaos=`) and/or installed into io.checkpoint
    (`install_io_faults()` / `with injector:`)."""

    def __init__(self, seed=0):
        self._rng = np.random.default_rng(seed)
        self._poison_steps = {}      # step -> var name or None (any)
        self._sigterm_steps = set()
        self._fail_writes = set()    # 1-based physical-write ordinals
        self._write_count = 0
        self.fired = {"poison": 0, "sigterm": 0, "write_fault": 0,
                      "cancel": 0, "clock_advance": 0,
                      "serving_poison": 0, "evict": 0,
                      "hash_collision": 0, "replica_kill": 0,
                      "replica_hang": 0, "replica_slow": 0,
                      "prompt_poison": 0, "spill": 0, "preempt": 0,
                      "process_kill": 0, "conn_drop": 0,
                      "fork_storm": 0, "mask_starve": 0}
        self._installed = False
        # serving-engine plan: iteration -> actions (scheduler hooks)
        self._serving_cancels = {}   # iteration -> [active-request index]
        self._serving_poisons = {}   # iteration -> KV layer to NaN
        self._clock_advances = {}    # iteration -> seconds to advance
        self._fake_now_s = 0.0
        self._drives_clock = False
        # prefix-cache plan (serving/prefix_cache.py hooks)
        self._serving_evicts = {}    # iteration -> evictions to force
        # KV tier plan (serving/scheduler.py host-tier hooks)
        self._serving_spills = {}    # iteration -> spills to force
        self._serving_preempts = {}  # iteration -> [request id]
        self._collide_hashes = set() # 1-based content-hash ordinals
        self._hash_count = 0
        # fleet plan (serving/router.py hooks)
        self._replica_kills = {}     # router iteration -> [replica idx]
        self._replica_hangs = {}     # router iteration -> [replica idx]
        self._replica_slow = {}      # replica idx -> ms per iteration
        self._slow_counted = set()   # replicas whose slow plan fired
        self._prompt_poisons = []    # (np.int32 prompt, kv layer)
        # out-of-process fleet plan (serving/router.py + transport.py)
        self._process_kills = {}     # router iteration -> [replica idx]
        self._conn_drops = {}        # 1-based rpc ordinal -> fault kind
        # fork-group plan (serving/scheduler.py + engine.py, ISSUE 20)
        self._fork_storms = {}       # iteration -> lanes to force-COW
        self._mask_starves = set()   # iterations to starve guided masks

    # -- plan ----------------------------------------------------------
    def poison_grad_at(self, step, var=None):
        """NaN a float feed entry of dispatch `step` (0-based trainer
        step): the forward then produces a NaN loss and every grad goes
        NaN — the sentinel path from real arithmetic, not a mock. `var`
        pins which feed entry; default: the first float feed (stable
        iteration order)."""
        self._poison_steps[int(step)] = var
        return self

    def sigterm_at(self, step):
        """Request preemption just before dispatching `step`, through
        the same flag the installed signal handler sets."""
        self._sigterm_steps.add(int(step))
        return self

    def fail_checkpoint_write(self, nth=1, times=1):
        """Fail physical checkpoint writes nth..nth+times-1 (1-based,
        counted across every file the io.checkpoint writers touch while
        this injector is installed)."""
        for i in range(int(nth), int(nth) + int(times)):
            self._fail_writes.add(i)
        return self

    # -- io fault hook (io.checkpoint._WRITE_FAULT_HOOK) ---------------
    def install_io_faults(self):
        from ..io import checkpoint as ckpt_mod
        ckpt_mod.set_write_fault_hook(self._on_checkpoint_write)
        self._installed = True
        return self

    def uninstall_io_faults(self):
        from ..io import checkpoint as ckpt_mod
        ckpt_mod.set_write_fault_hook(None)
        self._installed = False

    def __enter__(self):
        return self.install_io_faults()

    def __exit__(self, *exc):
        self.uninstall_io_faults()
        return False

    def _on_checkpoint_write(self, kind, path):
        self._write_count += 1
        if self._write_count in self._fail_writes:
            self.fired["write_fault"] += 1
            raise CheckpointWriteFault(
                f"chaos: injected failure on checkpoint write "
                f"#{self._write_count} ({kind}: {path})")

    @property
    def write_count(self):
        return self._write_count

    # -- serving hooks (serving/scheduler.py) --------------------------
    def cancel_request_at(self, iteration, index=0):
        """Cancel the index-th OLDEST active request at the start of
        scheduler iteration `iteration` (1-based, like the scheduler's
        own counter) — the deterministic mid-stream-cancel path for the
        continuous-batching engine."""
        self._serving_cancels.setdefault(int(iteration), []).append(
            int(index))
        return self

    def advance_clock_at(self, iteration, ms):
        """Advance the injected serving clock by `ms` at the start of
        iteration `iteration`. Pair with `serving_clock` as the
        scheduler's clock so deadline expiry is an exact iteration
        count, never a sleep."""
        self._clock_advances[int(iteration)] = \
            self._clock_advances.get(int(iteration), 0.0) + ms / 1e3
        self._drives_clock = True
        return self

    def tick_clock(self, ms):
        """Advance the injected serving clock NOW — the unconditional
        twin of advance_clock_at. A per-iteration plan pops each key
        ONCE, so a fleet whose engine population changes mid-run (an
        autoscaler spawning replicas whose fresh engines re-walk
        iteration numbers the plan already consumed) starves the
        clock; fleet tests tick it directly between requests
        instead."""
        self._fake_now_s += float(ms) / 1e3
        self._drives_clock = True
        self.fired["clock_advance"] += 1
        return self

    def serving_clock(self):
        """Deterministic clock (seconds) driven by advance_clock_at."""
        return self._fake_now_s

    def drives_clock(self):
        return self._drives_clock

    def on_serving_iteration(self, iteration):
        adv = self._clock_advances.pop(int(iteration), None)
        if adv is not None:
            self._fake_now_s += adv
            self.fired["clock_advance"] += 1

    def serving_cancels_at(self, iteration):
        idxs = self._serving_cancels.pop(int(iteration), [])
        self.fired["cancel"] += len(idxs)
        return idxs

    def poison_serving_at(self, iteration, layer=0):
        """NaN the first live KV block of the oldest active lane just
        before engine iteration `iteration` runs its fused step. The
        NaN flows through REAL attention arithmetic into that lane's
        logits, so the engine's non-finite guard trips on genuine
        propagation (flight-recorder dump + NonFiniteError), not a
        mocked output."""
        self._serving_poisons[int(iteration)] = int(layer)
        return self

    def serving_poison_at(self, iteration):
        """-> the KV layer to poison at this iteration, or None.
        Consumed by GenerationServer.step(). `fired` counts only when
        the engine reports the poison APPLIED (serving_poison_applied)
        — a step with no lane past position 0 defers to the next
        iteration instead of silently no-op'ing (a pos-0 lane's block
        is fully overwritten by its own prefill write, so the NaN
        could never propagate)."""
        return self._serving_poisons.pop(int(iteration), None)

    def serving_poison_applied(self):
        self.fired["serving_poison"] += 1

    # -- prefix-cache hooks (serving/prefix_cache.py) ------------------
    def evict_block_at(self, iteration, n=1):
        """Force `n` LRU prefix-cache evictions at the start of
        scheduler iteration `iteration` (1-based) — the deterministic
        eviction-under-pressure path, testable without streaming enough
        requests to actually exhaust the pool. Fires only for blocks
        that ARE evictable (idle leaf entries); a plan keyed to an
        iteration with nothing evictable is a no-op by design (tests
        arrange an idle entry first)."""
        self._serving_evicts[int(iteration)] = \
            self._serving_evicts.get(int(iteration), 0) + int(n)
        return self

    def serving_evictions_at(self, iteration):
        """-> number of forced evictions planned for this iteration.
        Consumed by the scheduler's plan(); `fired["evict"]` is counted
        by `serving_eviction_applied` only when a block was actually
        evicted."""
        return self._serving_evicts.pop(int(iteration), 0)

    def serving_eviction_applied(self):
        self.fired["evict"] += 1

    # -- KV tier hooks (serving/scheduler.py host tier) ----------------
    def spill_chain_at(self, iteration, n=1):
        """Force `n` prefix-chain spills (device->host evictions) at
        the start of scheduler iteration `iteration` (1-based) — the
        deterministic tiering path, testable without streaming enough
        requests to exhaust the device pool. Fires only when the
        eviction actually took the spill path (host tier attached,
        with space, and an idle leaf entry to take) — the plan is a
        no-op otherwise, exactly like evict_block_at."""
        self._serving_spills[int(iteration)] = \
            self._serving_spills.get(int(iteration), 0) + int(n)
        return self

    def serving_spills_at(self, iteration):
        """-> number of forced spills planned for this iteration.
        Consumed by the scheduler's plan(); `fired["spill"]` is counted
        by serving_spill_applied only when an eviction went
        device->host (destroy-evictions don't count)."""
        return self._serving_spills.pop(int(iteration), 0)

    def serving_spill_applied(self):
        self.fired["spill"] += 1

    def preempt_request_at(self, iteration, rid):
        """Park in-flight request `rid` in the host KV tier at the
        start of scheduler iteration `iteration` (1-based): its blocks
        swap out, its position/stream state stays queued, and the
        normal resume path swaps it back in when blocks free up (next
        iteration at the earliest — the park must span a real step).
        Fires only when `rid` was an active DECODE lane and the host
        pool held its blocks; no sleeps, injected-clock friendly."""
        self._serving_preempts.setdefault(int(iteration), []).append(
            rid)
        return self

    def serving_preempts_at(self, iteration):
        """-> request ids to preempt at this iteration. Consumed by
        the scheduler's plan(); `fired["preempt"]` counts via
        serving_preempt_applied only when a lane was actually parked."""
        return self._serving_preempts.pop(int(iteration), [])

    def serving_preempt_applied(self):
        self.fired["preempt"] += 1

    # -- fork-group hooks (serving/scheduler.py + engine.py) -----------
    def fork_storm_at(self, iteration, k=1):
        """Force copy-on-write divergence on up to `k` live fork-group
        lanes at the start of scheduler iteration `iteration` (1-based):
        each targeted lane's current block is COW'd even though nothing
        wrote it — the max-divergence burst path (every lane peels off
        the shared prompt at once), testable without arranging K real
        divergent writes. Fires (fork_storm_applied) only for lanes
        whose current block was actually copied — held followers,
        prefilling leaders, and lanes sitting on a NULL block are
        skipped by design."""
        self._fork_storms[int(iteration)] = \
            self._fork_storms.get(int(iteration), 0) + int(k)
        return self

    def fork_storms_at(self, iteration):
        """-> number of forced fork-COWs planned for this iteration.
        Consumed by the scheduler's plan(); `fired["fork_storm"]`
        counts via fork_storm_applied only for lanes actually COW'd."""
        return self._fork_storms.pop(int(iteration), 0)

    def fork_storm_applied(self, n=1):
        self.fired["fork_storm"] += int(n)

    def mask_starve_at(self, iteration):
        """Starve every guided-decoding lane's token mask at engine
        iteration `iteration` (1-based): the mask keeps exactly ONE
        allowed token (the lowest-id member of the constraint's allowed
        set), so generation stays conformant but the lane is forced
        down a single path — the degenerate-mask resilience path (the
        serving loop must keep stepping, never raise). Fires only when
        a guided lane was planned that iteration."""
        self._mask_starves.add(int(iteration))
        return self

    def mask_starves_at(self, iteration):
        """-> True if guided masks should be starved this iteration.
        Consumed by GenerationServer.step(); `fired["mask_starve"]`
        counts via mask_starve_applied only when a guided lane's mask
        was actually narrowed."""
        if int(iteration) in self._mask_starves:
            self._mask_starves.discard(int(iteration))
            return True
        return False

    def mask_starve_applied(self):
        self.fired["mask_starve"] += 1

    def hash_collision_at(self, nth, times=1):
        """Make content-hash computations nth..nth+times-1 (1-based,
        counted across every chunk the PrefixCacheIndex hashes while
        this injector is attached) return the COLLISION SENTINEL
        instead of the real hash. Two different chunks forced onto the
        sentinel collide in the index, and the token-verify fallback
        (collision -> miss, never another prompt's KV) is exercised on
        the real lookup path."""
        for i in range(int(nth), int(nth) + int(times)):
            self._collide_hashes.add(i)
        return self

    def prefix_hash_collides(self):
        self._hash_count += 1
        if self._hash_count in self._collide_hashes:
            self.fired["hash_collision"] += 1
            return True
        return False

    # -- fleet hooks (serving/router.py) -------------------------------
    def kill_replica_at(self, iteration, replica):
        """Kill fleet replica index `replica` at the START of router
        iteration `iteration` (1-based, the FleetRouter's own counter —
        a router iteration only counts when the fleet has work, so the
        plan is an exact point in the stream, never a wall-clock
        race). The kill is the real death path: the engine closes
        without drain, every in-flight future fails, and the router's
        failover re-admits them on survivors — mirroring
        poison_serving_at/evict_block_at, no sleeps anywhere."""
        self._replica_kills.setdefault(int(iteration), []).append(
            int(replica))
        return self

    def replica_kills_at(self, iteration):
        """-> replica indices to kill at this router iteration.
        Consumed by FleetRouter.step(); `fired["replica_kill"]` counts
        via replica_kill_applied only when a LIVE replica was actually
        torn down (a plan naming an already-dead replica is a no-op)."""
        return self._replica_kills.pop(int(iteration), [])

    def replica_kill_applied(self):
        self.fired["replica_kill"] += 1

    def hang_replica_at(self, iteration, replica):
        """Make fleet replica index `replica` HANG from the start of
        router iteration `iteration` (1-based): the router stops
        pumping its engine, so the replica stalls — queue and slots
        frozen mid-stream — WITHOUT dying. Nothing fails a future, so
        failover never triggers; only the supervisor's watchdog (stale
        progress marks across N heartbeats) can catch it. The hang is
        standing: it ends when the watchdog declares the replica hung
        and tears it down."""
        self._replica_hangs.setdefault(int(iteration), []).append(
            int(replica))
        return self

    def replica_hangs_at(self, iteration):
        """-> replica indices that begin hanging at this router
        iteration. Consumed by FleetRouter.step();
        `fired["replica_hang"]` counts via replica_hang_applied only
        when a LIVE replica actually started stalling."""
        return self._replica_hangs.pop(int(iteration), [])

    def replica_hang_applied(self):
        self.fired["replica_hang"] += 1

    def kill_process_at(self, iteration, replica):
        """SIGKILL the subprocess behind fleet replica index `replica`
        at the START of router iteration `iteration` (1-based, same
        counter as kill_replica_at). Unlike kill_replica_at — which
        closes an in-process engine through its own close path — this
        is a REAL `os.kill(pid, SIGKILL)`: the worker gets no chance
        to flush, the parent only learns of the death when the next
        RPC fails, and the whole dead-replica pipeline (failover,
        supervisor resurrection) must hold against an actual process
        corpse. `fired["process_kill"]` counts via
        process_kill_applied only when a live worker pid was
        actually signalled."""
        self._process_kills.setdefault(int(iteration), []).append(
            int(replica))
        return self

    def process_kills_at(self, iteration):
        """-> replica indices whose worker process to SIGKILL at this
        router iteration. Consumed by FleetRouter.step()."""
        return self._process_kills.pop(int(iteration), [])

    def process_kill_applied(self):
        self.fired["process_kill"] += 1

    def drop_connection_at(self, nth, kind="reset"):
        """Inject exactly one transport fault on the `nth` RPC call
        (1-based, counted per RpcClient) of any client built with this
        injector. kind="reset" drops the connection before the send —
        the client's bounded-backoff retry path must recover; kind=
        "timeout" makes the call time out — the proxy's hung-suspect
        classification path must engage. Deterministic (keyed to the
        call ordinal, no sleeps needed to hit the window)."""
        if kind not in ("reset", "timeout"):
            raise ValueError(
                f"drop_connection_at kind must be 'reset' or "
                f"'timeout', got {kind!r}")
        self._conn_drops[int(nth)] = kind
        return self

    def conn_drop_for(self, ncall):
        """-> the fault kind planned for this rpc ordinal, or None.
        Consumed by transport.RpcClient; counts fired["conn_drop"]
        when a fault is actually injected."""
        kind = self._conn_drops.pop(int(ncall), None)
        if kind is not None:
            self.fired["conn_drop"] += 1
        return kind

    def slow_replica(self, replica, ms_per_iteration):
        """Standing plan: every pump of fleet replica index `replica`
        reports an extra `ms_per_iteration` of step time to the
        router's per-replica timing (the watchdog's slow-classification
        input). The replica still advances — progress marks move — so
        the watchdog must label it `slow`, never hung or dead."""
        self._replica_slow[int(replica)] = float(ms_per_iteration)
        return self

    def replica_slow_ms(self, replica):
        """-> the injected extra ms for this replica's pumps, or None.
        Counted once on first application (the plan is standing)."""
        ms = self._replica_slow.get(int(replica))
        if ms is not None and int(replica) not in self._slow_counted:
            self._slow_counted.add(int(replica))
            self.fired["replica_slow"] += 1
        return ms

    def poison_prompt(self, prompt_ids, layer=0):
        """Mark a PROMPT as poison: whenever a lane serving exactly
        this prompt has advanced past position 0, the engine NaNs the
        lane's first KV block (layer `layer`) before its next fused
        step — the NaN flows through real attention into that lane's
        logits and trips the non-finite fail-stop. Unlike
        poison_serving_at (keyed to one engine iteration), this plan is
        STANDING and content-addressed, so the request's failover
        REPLAY re-faults every replica it lands on — the deterministic
        poison-request cascade the router's quarantine exists to stop
        (fired["prompt_poison"] counts one per application, i.e. one
        per replica death it causes)."""
        self._prompt_poisons.append(
            (np.asarray(prompt_ids, np.int32).reshape(-1), int(layer)))
        return self

    def prompt_poison_plan(self):
        """-> the standing [(prompt, layer)] plan (empty list when no
        prompt is marked). Consumed every engine step."""
        return self._prompt_poisons

    def prompt_poison_applied(self):
        self.fired["prompt_poison"] += 1

    # -- trainer hooks -------------------------------------------------
    def should_preempt(self, step):
        if int(step) in self._sigterm_steps:
            self._sigterm_steps.discard(int(step))
            self.fired["sigterm"] += 1
            return True
        return False

    def on_dispatch(self, step, feed):
        """Return the (possibly poisoned) feed for dispatch `step`.
        Fires at most once per planned step — a replay after rollback
        gets the clean original."""
        var = self._poison_steps.pop(int(step), "*absent*")
        if var == "*absent*":
            return feed
        target = var
        if target is None:
            for k in sorted(feed):
                v = np.asarray(feed[k])
                if np.issubdtype(v.dtype, np.floating):
                    target = k
                    break
        if target is None or target not in feed:
            raise ValueError(
                f"chaos: no float feed entry to poison at step {step} "
                f"(feed keys: {sorted(feed)})")
        bad = np.array(np.asarray(feed[target]), copy=True)
        bad.ravel()[0] = np.nan
        self.fired["poison"] += 1
        out = dict(feed)
        out[target] = bad
        return out
