"""NaN/Inf sentinels for the jitted training step.

The reference framework surfaces divergence as a mid-run crash (an op
kernel hits a NaN and the whole run dies numberless); the TPU-native
runtime folds a cheap `isfinite` reduction INTO the compiled step — one
fused all-reduce over the loss, every `var@GRAD`, and the fetched float
vars, returned to the host as a vector of one bool per monitored var.
The host check is a tiny sync that rides the fetch the caller was going
to pay anyway; a tripped sentinel raises a structured NonFiniteError at
run() / FetchHandle.result() / drain() identifying the FIRST bad var
and the step it went bad on, so a GuardedTrainer (robustness/trainer.py)
can roll back instead of writing a poisoned checkpoint.

Opt-in per executor: `Executor(guard=True)` / `Executor(guard=
GuardConfig(...))`, or process-wide via `PADDLE_TPU_GUARD=1`.
"""

__all__ = ["GuardConfig", "NonFiniteError"]

_FALSY = ("", "0", "false", "no", "off")


class GuardConfig:
    """What the in-step sentinel monitors.

    check_loss    — the backward marker's loss var (training programs).
    check_grads   — every param's `var@GRAD` (the earliest place
                    divergence is visible: one bad grad poisons the
                    whole donated state on the NEXT step).
    check_fetches — floating-point fetch_list entries (inference /
                    forward-only programs have no grads to watch).
    extra_vars    — additional env var names to monitor (e.g. an AMP
                    `loss_scaling` accumulator).
    """

    __slots__ = ("check_loss", "check_grads", "check_fetches",
                 "extra_vars")

    def __init__(self, check_loss=True, check_grads=True,
                 check_fetches=True, extra_vars=()):
        self.check_loss = bool(check_loss)
        self.check_grads = bool(check_grads)
        self.check_fetches = bool(check_fetches)
        self.extra_vars = tuple(extra_vars)

    @staticmethod
    def resolve(value):
        """None/False/falsy env -> None (guard off); True/truthy env ->
        default GuardConfig; a GuardConfig passes through."""
        if value is None or value is False:
            return None
        if isinstance(value, GuardConfig):
            return value
        if isinstance(value, str):
            return None if value.strip().lower() in _FALSY \
                else GuardConfig()
        return GuardConfig() if value else None

    def candidates(self, loss_name, grad_names, fetch_names):
        """Ordered, de-duplicated monitor list (static at trace time)."""
        out, seen = [], set()

        def add(n):
            if n and n not in seen:
                seen.add(n)
                out.append(n)

        if self.check_loss and loss_name:
            add(loss_name)
        if self.check_grads:
            for n in grad_names:
                add(n)
        if self.check_fetches:
            for n in fetch_names:
                add(n)
        for n in self.extra_vars:
            add(n)
        return out


class NonFiniteError(ArithmeticError):
    """A guarded step produced NaN/Inf in a monitored var.

    Attributes:
        var      — first monitored var that went non-finite
                   (monitor order: loss, grads, fetches, extras);
        step     — the executor step counter of the offending step
                   (the value its RNG folded in; stable across replay);
        bad_vars — every monitored var that tripped this step.

    Raised at the point results are OBSERVED — Executor.run(),
    FetchHandle.wait()/result(), Executor.drain() — never inside the
    async dispatch, so pipeline order survives a poisoned step.
    """

    def __init__(self, var, step, bad_vars=None):
        self.var = var
        self.step = int(step)
        self.bad_vars = list(bad_vars if bad_vars is not None else [var])
        super().__init__(
            f"non-finite value detected in '{var}' at step {self.step}"
            + (f" (also bad: {self.bad_vars[1:]})"
               if len(self.bad_vars) > 1 else ""))
