"""Self-healing fleet supervision: watchdog, resurrection, quarantine.

The fleet front door (serving/router.py) survives a replica death only
by SHRINKING: killed replicas never come back, a replica that *hangs*
(stuck engine iteration — no death, no failed future, nothing raises)
is never detected, and a request whose replay deterministically faults
the engine is re-admitted on survivor after survivor until the whole
fleet is gone. This module is the missing control loop, the TPU-way
re-expression of Fluid 1.5 pserver-mode fault-tolerant serving
(SURVEY §1): a fleet is defined by what it survives.

Three mechanisms, one heartbeat (``FleetSupervisor.on_heartbeat``,
driven by ``FleetRouter.step()`` — so the whole tier runs under the
injected serving clock, with zero wall-clock dependence):

- **Watchdog** — per-replica progress marks (``Replica.progress_mark``:
  scheduler iteration + token/admission counters, pure counter reads).
  A replica with work whose mark is frozen for ``hang_heartbeats``
  consecutive heartbeats is declared HUNG: torn down like a death
  (its stream registrations drain — the dead engine is never pumped
  again, so no late token can reach a client), its in-flight requests
  re-admitted bitwise on survivors by the router's failover path. A
  replica whose pumps run but take longer than ``slow_ms`` is labeled
  ``slow`` (surfaced in health/stats — an operator signal, not a
  teardown: slow is a capacity problem, hung is a correctness one).
  Dead replicas (kill, engine fault) are picked up the same heartbeat.

- **Resurrection** — a failed replica slot is respawned through
  ``spawn_fn(index)`` under a crash-loop circuit breaker: exponential
  backoff measured in heartbeats (``backoff_heartbeats`` ·
  ``backoff_factor``^failures), a half-open PROBE request served
  end-to-end before the replica rejoins rotation, and permanent
  eviction after ``max_crash_loops`` consecutive failures (a failed
  spawn/probe, or a resurrected replica dying again before retiring a
  single request). ``make_checkpoint_spawn`` builds a spawn_fn that
  reloads weights through robustness/checkpoint_manager.py (newest
  valid checkpoint, CRC-validated, walking back past corrupt ones).
  Before rejoining, the new replica's prefix cache is RE-WARMED from
  the router's fleet-wide ``ChunkPopularityDigest`` — the most popular
  prompt chains re-prefill into its index, so a resurrected replica
  rejoins near its pre-death hit rate instead of cold.

- **Quarantine** (lives in the router; this module owns the error) —
  the router tracks per-request failover lineage; a request implicated
  in ``poison_threshold`` (default 2) replica deaths is failed with a
  structured ``PoisonRequestError`` and recorded in the fleet flight
  recorder instead of cascading onward.

Everything is deterministic: heartbeats are router iterations, backoff
is heartbeat counts, probes and warm-ups pump manual-drive engines
synchronously. docs/robustness.md "Self-healing fleet" has the tuning
guide; metrics are ``serving.fleet.{hangs,resurrections,crash_loops,
quarantines}``.
"""

import numpy as np

__all__ = ["FleetSupervisor", "SupervisorConfig", "PoisonRequestError",
           "ChunkPopularityDigest", "make_checkpoint_spawn",
           "Autoscaler", "AutoscalerConfig"]


class PoisonRequestError(RuntimeError):
    """The router quarantined this request: its failover lineage
    implicates it in `deaths` replica deaths (engine faults naming its
    lane), so re-admitting it again would predictably kill another
    replica. `lineage` lists the deaths it was present for (replica
    name + kind), `attempts` the failovers it consumed, `flight_dump`
    the fleet flight-recorder artifact written at quarantine time."""

    def __init__(self, message, request_id, lineage, attempts,
                 flight_dump=None):
        super().__init__(message)
        self.request_id = request_id
        self.lineage = list(lineage)
        self.deaths = sum(1 for d in self.lineage if d.get("implicated"))
        self.attempts = attempts
        self.flight_dump = flight_dump


class ChunkPopularityDigest:
    """Fleet-wide chunk popularity, kept by the router: chain key ->
    (parent key, chunk tokens, hit count). Fed on every submit from
    the prompt's chain keys (the same blake2b chain the per-replica
    prefix indexes use), consumed by resurrection to re-warm a fresh
    replica's cache with the prompts the FLEET actually serves —
    popularity survives any single replica's death because it lives
    here, not in the dead index.

    Bounded: past `max_entries` the least-popular entries are dropped;
    a chain whose ancestor was dropped reconstructs to None and is
    skipped at warm time (re-warm is best-effort by design)."""

    def __init__(self, max_entries=512):
        self.max_entries = int(max_entries)
        self._entries = {}          # key -> [parent, tokens, hits]

    def observe(self, keys, prompt, block_size):
        """Record one routed prompt's full chunks."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        parent = None
        for i, key in enumerate(keys):
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = [
                    parent, np.array(
                        prompt[i * block_size:(i + 1) * block_size],
                        np.int32, copy=True), 1]
            else:
                e[2] += 1
            parent = key
        if len(self._entries) > self.max_entries:
            self._shrink()

    def _shrink(self):
        keep = sorted(self._entries.items(),
                      key=lambda kv: kv[1][2],
                      reverse=True)[:self.max_entries]
        self._entries = dict(keep)

    def forget(self, keys):
        """Drop these chain keys (the router calls this when a request
        is QUARANTINED: re-warming a resurrected replica with the very
        prompt whose replay faults engines would re-enter the cascade
        through the healing path). Descendants that survive reconstruct
        to None once an ancestor is gone and are skipped at warm
        time."""
        for key in keys:
            self._entries.pop(key, None)

    def prompt_for(self, key):
        """Reconstruct the full token prefix ending at chain `key` by
        walking parents to the root; None when the chain is broken
        (an ancestor was shrunk away)."""
        chunks = []
        while key is not None:
            e = self._entries.get(key)
            if e is None:
                return None
            chunks.append(e[1])
            key = e[0]
        return np.concatenate(list(reversed(chunks)))

    def top_chains(self, n):
        """The `n` most popular chain endpoints, deepest-first within
        a chain (a taken key covers all its ancestors, so they are
        skipped — warming the deepest chunk warms the whole prefix)."""
        parents = {e[0] for e in self._entries.values()
                   if e[0] is not None}
        ranked = sorted(self._entries.items(),
                        key=lambda kv: (kv[1][2], kv[0]), reverse=True)
        taken, covered = [], set()
        for key, _e in ranked:
            if key in covered:
                continue
            # prefer leaves; an interior key only if no descendant of
            # it was (or will be) taken — approximated by skipping
            # keys that are parents of a live entry with >= its hits
            if key in parents:
                continue
            taken.append(key)
            walk = key
            while walk is not None:
                covered.add(walk)
                walk = self._entries[walk][0] \
                    if walk in self._entries else None
            if len(taken) >= n:
                break
        if len(taken) < n:
            # chains may be all-interior after a shrink: top up with
            # the most popular uncovered keys of any shape
            for key, _e in ranked:
                if key not in covered:
                    taken.append(key)
                    covered.add(key)
                    if len(taken) >= n:
                        break
        return taken

    def __len__(self):
        return len(self._entries)

    def stats(self):
        return {"entries": len(self._entries),
                "max_entries": self.max_entries}


class SupervisorConfig:
    """Tuning knobs for FleetSupervisor (docs/robustness.md
    "Self-healing fleet" walks through each):

    - hang_heartbeats: consecutive no-progress heartbeats (with work
      pending) before a replica is declared hung and torn down.
    - slow_ms: pump-time EMA above which a replica is labeled `slow`
      (None disables the classification).
    - resurrect: respawn failed replicas (needs the router's spawn_fn).
    - backoff_heartbeats / backoff_factor: crash-loop circuit breaker
      delay = backoff_heartbeats * backoff_factor**failures, in
      heartbeats (deterministic under injected clocks).
    - max_crash_loops: consecutive failed resurrections (or
      die-before-serving relapses) before the slot is PERMANENTLY
      evicted.
    - probe_tokens / probe_timeout_s: the half-open probe request a
      respawned engine must serve end-to-end before rejoining.
    - warm_chains: how many popular prompt chains to re-prefill into
      the resurrected replica's prefix cache (0 disables re-warm).
    """

    def __init__(self, hang_heartbeats=3, slow_ms=None, resurrect=True,
                 backoff_heartbeats=2, backoff_factor=2.0,
                 max_crash_loops=3, probe_tokens=2, probe_timeout_s=30.0,
                 warm_chains=8):
        if hang_heartbeats < 1:
            raise ValueError("hang_heartbeats must be >= 1")
        if max_crash_loops < 1:
            raise ValueError("max_crash_loops must be >= 1")
        self.hang_heartbeats = int(hang_heartbeats)
        self.slow_ms = None if slow_ms is None else float(slow_ms)
        self.resurrect = bool(resurrect)
        self.backoff_heartbeats = int(backoff_heartbeats)
        self.backoff_factor = float(backoff_factor)
        self.max_crash_loops = int(max_crash_loops)
        self.probe_tokens = int(probe_tokens)
        self.probe_timeout_s = float(probe_timeout_s)
        self.warm_chains = int(warm_chains)


class FleetSupervisor:
    """The router's self-healing control loop. Constructed by
    FleetRouter (``supervisor=True`` / ``SupervisorConfig(...)``) and
    driven by its step(): one on_heartbeat() per router iteration —
    plus idle ticks while any duty (a pending resurrection backoff)
    remains, so manual-drive ``run_until_idle`` keeps pumping until
    the fleet is back at strength."""

    def __init__(self, router, config=None):
        self.router = router
        self.config = config or SupervisorConfig()
        self.heartbeat = 0
        self._watch = {}        # index -> [last mark, stale count]
        self._breaker = {}      # index -> breaker dict
        self.counts = {"hangs": 0, "resurrections": 0, "crash_loops": 0,
                       "evictions": 0, "slow_flags": 0, "probes": 0,
                       "warm_prompts": 0}

    # -- heartbeat ---------------------------------------------------------
    def on_heartbeat(self):
        """One supervision pass over every replica slot. Returns True
        when the supervisor acted or still owes work (a backoff timer
        pending) — the router's step() treats that as fleet activity."""
        self.heartbeat += 1
        did = False
        for r in list(self.router._replicas):
            if r.state in ("evicted", "drained"):
                continue
            if not r.alive():
                did = self._supervise_dead(r) or did
                continue
            # a previously-resurrected replica that has now served
            # CLIENT traffic (retired anything beyond its own probe +
            # warm-up requests — the adoption baseline) closes its
            # crash-loop window
            b = self._breaker.get(r.index)
            if b and b["failures"] and r.generation > 0 and \
                    r.server._sched.counts["retired"] > \
                    b.get("adopted_retired", 0):
                b["failures"] = 0
            did = self._watchdog(r) or did
        return did

    def _watchdog(self, r):
        cfg = self.config
        mark = r.progress_mark()
        w = self._watch.setdefault(r.index, [mark, 0])
        if r.server._sched.has_work() and mark == w[0]:
            w[1] += 1
            if w[1] >= cfg.hang_heartbeats:
                w[0], w[1] = mark, 0
                self.counts["hangs"] += 1
                self.router._declare_hung(r.index)
                return True
        else:
            w[0], w[1] = mark, 0
        if cfg.slow_ms is not None and r.step_ms_ema is not None:
            verdict = "slow" if r.step_ms_ema > cfg.slow_ms else "ok"
            if verdict == "slow" and r.condition != "slow":
                self.counts["slow_flags"] += 1
            r.condition = verdict
        return False

    # -- resurrection ------------------------------------------------------
    def _supervise_dead(self, r):
        if not self.config.resurrect or self.router.spawn_fn is None:
            return False
        b = self._breaker.get(r.index)
        if b is None:
            b = {"failures": 0, "retry_at": 0, "relapse_gen": None}
            self._breaker[r.index] = b
        # a resurrected replica that died again before retiring a
        # single CLIENT request (beyond its own probe/warm-up — the
        # adoption baseline) is a crash-loop relapse — count it once
        # per generation, toward permanent eviction
        if r.generation > 0 and b["relapse_gen"] != r.generation and \
                r.server._sched.counts["retired"] <= \
                b.get("adopted_retired", 0):
            b["relapse_gen"] = r.generation
            self._crash_loop(r, b, "died before serving any request")
            if r.state == "evicted":
                return False
        if b["retry_at"] == 0:
            b["retry_at"] = self.heartbeat + self._backoff(b["failures"])
            return True
        if self.heartbeat < b["retry_at"]:
            return True     # duty pending: keep the router stepping
        b["retry_at"] = 0
        self._attempt_resurrection(r, b)
        return True

    def _backoff(self, failures):
        return max(1, round(self.config.backoff_heartbeats
                            * self.config.backoff_factor ** failures))

    def _crash_loop(self, r, b, why):
        b["failures"] += 1
        self.counts["crash_loops"] += 1
        self.router._count_fleet("crash_loops")
        self.router._flight_event(
            "crash_loop", replica=r.name, failures=b["failures"],
            why=why)
        if b["failures"] >= self.config.max_crash_loops:
            r.state = "evicted"
            self.counts["evictions"] += 1
            self.router._flight_event(
                "replica_evicted", replica=r.name,
                crash_loops=b["failures"])
            self.router._publish_gauges()

    def _attempt_resurrection(self, r, b):
        router = self.router
        try:
            server = router.spawn_fn(r.index)
        except Exception as e:      # noqa: BLE001 — spawn is user code
            self._crash_loop(r, b, f"spawn_fn raised: {e!r}")
            return
        try:
            if server.block_size != router._block_size:
                raise ValueError(
                    f"spawn_fn returned block_size "
                    f"{server.block_size}, fleet uses "
                    f"{router._block_size} (affinity keys chunk by it)")
            self._probe(server)
            self._warm(server)
        except Exception as e:      # noqa: BLE001 — half-open probe:
            #                         ANY failure re-opens the breaker
            try:
                server.close(drain=False)
            except Exception:       # noqa: BLE001
                pass
            self._crash_loop(r, b, f"probe/warm failed: {e!r}")
            return
        router._adopt_replica(r.index, server,
                              generation=r.generation + 1)
        self._watch.pop(r.index, None)
        b["relapse_gen"] = None
        # relapse baseline: retirements at adoption are the probe +
        # warm-ups, not client traffic
        b["adopted_retired"] = server._sched.counts["retired"]
        self.counts["resurrections"] += 1
        router._count_fleet("resurrections")
        router._flight_event(
            "resurrection", replica=r.name,
            generation=r.generation + 1,
            warmed_chains=min(self.config.warm_chains,
                              len(router._digest)))

    def _probe(self, server):
        """Half-open probe: the respawned engine must serve ONE real
        request end-to-end before any client traffic routes to it."""
        cfg = self.config
        prompt = self._probe_prompt()
        fut = server.submit(prompt, max_new_tokens=cfg.probe_tokens)
        self._pump(server, [fut])
        fut.result(timeout=cfg.probe_timeout_s)
        self.counts["probes"] += 1

    def _probe_prompt(self):
        """The most popular cached chain doubles as the probe payload
        (it exercises the exact path production traffic will); with an
        empty digest, a minimal token-0 prompt."""
        digest = self.router._digest
        for key in digest.top_chains(1):
            p = digest.prompt_for(key)
            if p is not None:
                return p
        return np.zeros(2, np.int32)

    def _warm(self, server):
        """Prefix re-warm: re-prefill the fleet's most popular prompt
        chains into the fresh replica's index BEFORE it rejoins, so
        affinity routing finds it warm. Best-effort: a chain that no
        longer fits (or was shrunk out of the digest) is skipped."""
        cfg = self.config
        if cfg.warm_chains <= 0 or server._prefix is None:
            return
        futs = []
        for key in self.router._digest.top_chains(cfg.warm_chains):
            prompt = self.router._digest.prompt_for(key)
            if prompt is None:
                continue
            try:
                futs.append(server.submit(prompt, max_new_tokens=1))
            except (ValueError, RuntimeError):
                continue    # too big for this pool / raced a close
        self._pump(server, futs)
        done = 0
        for f in futs:
            f.result(timeout=cfg.probe_timeout_s)
            done += 1
        self.counts["warm_prompts"] += done

    @staticmethod
    def _pump(server, futs):
        """Drive a not-yet-adopted server: manual-drive engines are
        pumped synchronously (deterministic tier); worker-threaded
        engines drain on their own and the caller waits on futures."""
        if server._worker is None:
            server.run_until_idle()

    def stats(self):
        return {
            "heartbeat": self.heartbeat,
            "config": {
                "hang_heartbeats": self.config.hang_heartbeats,
                "slow_ms": self.config.slow_ms,
                "max_crash_loops": self.config.max_crash_loops,
                "backoff_heartbeats": self.config.backoff_heartbeats,
                "warm_chains": self.config.warm_chains,
            },
            "breaker": {
                i: {"failures": b["failures"],
                    "retry_at_heartbeat": b["retry_at"] or None}
                for i, b in self._breaker.items()},
            **dict(self.counts),
        }


class AutoscalerConfig:
    """Tuning knobs for the SLO-driven Autoscaler (docs/robustness.md
    "Autoscaler safety rail" and docs/serving.md "Out-of-process
    fleet" walk through each):

    - min_replicas / max_replicas: hard fleet-size bounds; the
      controller never drains below the floor or spawns past the
      ceiling, no matter what the burn series says.
    - targets: check_slo's shape ({"ttft_ms": {"p95": 250.0}, ...}) —
      the SLOs whose windowed burn rates drive scaling. The router
      folds these into its ``slo.window_burn.<metric>.<qtag>`` series
      sampling, so an autoscaled fleet needs no AdmissionPolicy for
      the series to exist.
    - up_threshold / down_threshold: burn-rate hysteresis band. Above
      up_threshold the error budget is actively burning (scale up);
      below down_threshold the fleet is comfortably over-provisioned
      (scale down); in between, hold. up > down is enforced — a
      touching band would oscillate.
    - up_samples / down_samples: consecutive NEW burn samples past the
      threshold before acting. Scale-up-fast / scale-down-slow is
      expressed here: the defaults react to 2 bad samples but demand 6
      calm ones — adding capacity late costs user latency, removing it
      early costs a re-spawn (and its cold caches) minutes later.
    - cooldown_heartbeats: router heartbeats to hold after ANY scale
      action before considering another — the new replica's effect
      must reach the burn series before the controller trusts it.

    Deterministic like everything in this module: samples are counted
    by series-point arrival (the router's injected signals clock), the
    cooldown in router heartbeats — no wall clocks."""

    def __init__(self, min_replicas=1, max_replicas=4, targets=None,
                 up_threshold=1.0, down_threshold=0.25,
                 up_samples=2, down_samples=6, cooldown_heartbeats=8):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_replicas ({min_replicas})")
        if not (up_threshold > down_threshold):
            raise ValueError(
                f"up_threshold ({up_threshold}) must exceed "
                f"down_threshold ({down_threshold}) — the hysteresis "
                f"band is what stops flapping")
        if up_samples < 1 or down_samples < 1:
            raise ValueError("up_samples/down_samples must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.targets = ({m: dict(q) for m, q in targets.items()}
                        if targets else {"ttft_ms": {"p95": 250.0}})
        self.up_threshold = float(up_threshold)
        self.down_threshold = float(down_threshold)
        self.up_samples = int(up_samples)
        self.down_samples = int(down_samples)
        self.cooldown_heartbeats = int(cooldown_heartbeats)


class Autoscaler:
    """SLO-driven fleet sizing: spawn and retire replica slots from
    the live windowed burn-rate series, with the crash-loop breaker
    as the safety rail.

    Constructed by FleetRouter (``autoscale=True`` /
    ``AutoscalerConfig(...)``) and driven by its step(): one
    on_heartbeat() per router iteration, exactly like the supervisor.
    The controller reads the fleet's ``slo.window_burn.<metric>.
    <qtag>`` series (PR 17 health signals — the ~2-window rolling
    view that decays after recovery, so a scale-up it causes can
    actually register as relief). Streaks count NEW series points,
    not heartbeats: idle heartbeats between signal samples neither
    age the evidence nor fake more of it.

    The safety rail: while any crash-loop breaker entry is open, any
    slot is permanently evicted, or any death is still awaiting
    resurrection, scale-ups are BLOCKED (counted in
    ``serving.fleet.autoscale.blocked``). A crashing image makes its
    own burn rate terrible — survivors absorb its load — and an
    autoscaler without this rail would read that as demand and spawn
    the same broken image in a storm. Capacity problems get capacity;
    health problems stay the supervisor's.

    Scale-up goes through ``router.add_replica_slot()`` (spawn_fn +
    fleet-contract validation); scale-down drains the least-loaded
    accepting replica — its in-flight work finishes, then the
    router's step() closes it. Metrics:
    ``serving.fleet.autoscale.{scale_ups,scale_downs,blocked}`` and
    the ``serving.fleet.autoscale.desired`` gauge."""

    def __init__(self, router, config=None):
        from ..observability import _help
        from ..observability.metrics import global_registry
        self.router = router
        self.config = config or AutoscalerConfig()
        self.heartbeat = 0
        self.desired = None         # set from live count, first tick
        self._last_t = None         # newest burn sample consumed
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0
        self.counts = {"scale_ups": 0, "scale_downs": 0, "blocked": 0,
                       "spawn_failures": 0, "samples": 0}
        reg = global_registry()
        self._m_ups = reg.counter(
            "serving.fleet.autoscale.scale_ups",
            _help("serving.fleet.autoscale.scale_ups"))
        self._m_downs = reg.counter(
            "serving.fleet.autoscale.scale_downs",
            _help("serving.fleet.autoscale.scale_downs"))
        self._m_blocked = reg.counter(
            "serving.fleet.autoscale.blocked",
            _help("serving.fleet.autoscale.blocked"))
        self._g_desired = reg.gauge(
            "serving.fleet.autoscale.desired",
            _help("serving.fleet.autoscale.desired"))

    # -- heartbeat ---------------------------------------------------------
    def on_heartbeat(self):
        """One control pass. Returns True when the fleet size changed
        (the router's step() treats that as activity)."""
        self.heartbeat += 1
        router = self.router
        live = [r for r in router._replicas if r.accepting()]
        if self.desired is None:
            self.desired = len(live)
        self._g_desired.labels(router=router.name).set(self.desired)
        point = self._worst_burn()
        if point is not None:
            t, worst = point
            if t != self._last_t:       # a NEW sample — fresh evidence
                self._last_t = t
                self.counts["samples"] += 1
                cfg = self.config
                if worst > cfg.up_threshold:
                    self._up_streak += 1
                    self._down_streak = 0
                elif worst < cfg.down_threshold:
                    self._down_streak += 1
                    self._up_streak = 0
                else:                   # inside the hysteresis band
                    self._up_streak = self._down_streak = 0
        if self.heartbeat < self._cooldown_until:
            return False
        cfg = self.config
        if self._up_streak >= cfg.up_samples:
            return self._try_scale_up(live)
        if self._down_streak >= cfg.down_samples:
            return self._try_scale_down(live)
        return False

    def _worst_burn(self):
        """The newest value across every configured burn series, as
        (t, worst); None before the first sample lands. Worst-of is
        the right fold: one breached SLO is a capacity problem even
        while the others are green."""
        store = getattr(self.router._signals, "fleet", None)
        if store is None:
            return None
        newest_t = worst = None
        for metric, qmap in self.config.targets.items():
            for tag in qmap:
                p = store.latest(f"slo.window_burn.{metric}.{tag}")
                if p is None:
                    continue
                t, v = p
                if newest_t is None or t > newest_t:
                    newest_t = t
                if worst is None or v > worst:
                    worst = v
        if newest_t is None:
            return None
        return (newest_t, worst)

    def _rail_open(self):
        """True while scale-ups must be blocked: an open crash-loop
        breaker entry, a permanently evicted slot, or a death still
        awaiting resurrection. All three mean the fleet is losing
        replicas to something a fresh spawn would inherit."""
        for r in self.router._replicas:
            if r.state == "evicted":
                return True
            if not r.alive() and r.state not in ("drained",):
                return True
        sup = self.router.supervisor
        if sup is not None:
            for b in sup._breaker.values():
                if b["failures"]:
                    return True
        return False

    def _try_scale_up(self, live):
        router = self.router
        if len(live) >= self.config.max_replicas:
            self._up_streak = 0     # at the ceiling: demand re-proves
            return False
        if self._rail_open():
            self.counts["blocked"] += 1
            self._m_blocked.inc()
            self._up_streak = 0
            router._flight_event(
                "scale_up_blocked", live=len(live),
                reason="crash-loop breaker / unresolved death")
            return False
        try:
            rep = router.add_replica_slot()
        except Exception as e:  # noqa: BLE001 — spawn_fn is user code
            self.counts["spawn_failures"] += 1
            self._up_streak = 0
            self._cooldown_until = (self.heartbeat
                                    + self.config.cooldown_heartbeats)
            router._flight_event("scale_up_failed", why=repr(e))
            return False
        self.counts["scale_ups"] += 1
        self._m_ups.inc()
        self.desired = len(live) + 1
        self._g_desired.labels(router=router.name).set(self.desired)
        self._up_streak = self._down_streak = 0
        self._cooldown_until = (self.heartbeat
                                + self.config.cooldown_heartbeats)
        router._flight_event("autoscale_up", replica=rep.name,
                             desired=self.desired)
        return True

    def _try_scale_down(self, live):
        router = self.router
        if len(live) <= self.config.min_replicas:
            self._down_streak = 0   # at the floor: calm re-proves
            return False
        # retire the least-loaded accepting replica: fewest in-flight
        # requests to finish, so the drain completes soonest
        victim = min(live, key=lambda r: r.load())
        router.drain_replica(victim.index)
        self.counts["scale_downs"] += 1
        self._m_downs.inc()
        self.desired = len(live) - 1
        self._g_desired.labels(router=router.name).set(self.desired)
        self._up_streak = self._down_streak = 0
        self._cooldown_until = (self.heartbeat
                                + self.config.cooldown_heartbeats)
        router._flight_event("autoscale_down", replica=victim.name,
                             desired=self.desired)
        return True

    def stats(self):
        return {
            "heartbeat": self.heartbeat,
            "desired": self.desired,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "cooldown_until": self._cooldown_until or None,
            "rail_open": self._rail_open(),
            "config": {
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
                "targets": {m: dict(q) for m, q in
                            self.config.targets.items()},
                "up_threshold": self.config.up_threshold,
                "down_threshold": self.config.down_threshold,
                "up_samples": self.config.up_samples,
                "down_samples": self.config.down_samples,
                "cooldown_heartbeats": self.config.cooldown_heartbeats,
            },
            **dict(self.counts),
        }


def make_checkpoint_spawn(manager, cfg, scope_factory=None,
                          executor=None, **server_kwargs):
    """Build a resurrection ``spawn_fn`` that reloads model weights
    through a robustness.CheckpointManager: each call restores the
    newest VALID checkpoint (CRC-validated, walking back past corrupt
    candidates) into a fresh scope and builds a GenerationServer over
    it — the serving twin of GuardedTrainer's rollback-restore.

        manager = CheckpointManager(root, program=main_program)
        router = FleetRouter(servers, spawn_fn=make_checkpoint_spawn(
            manager, gpt_cfg, num_slots=4, start=False),
            supervisor=True)

    `server_kwargs` pass through to GenerationServer (start=False for
    manual-drive fleets). Raises CheckpointError/CheckpointCorruptError
    when no loadable checkpoint exists — the supervisor counts that as
    a crash-loop, exactly like a failed spawn."""
    from ..core.executor import Executor, Scope
    from ..models.gpt import load_params
    from ..serving.engine import GenerationServer, GPTServingModel
    from .checkpoint_manager import CheckpointError

    def spawn(index):
        scope = (scope_factory or Scope)()
        exe = executor if executor is not None else Executor()
        meta = manager.restore(exe, scope=scope,
                               restore_step_counter=False)
        if meta is None:
            raise CheckpointError(
                f"resurrection of replica {index}: no checkpoint "
                f"under {manager.root}")
        model = GPTServingModel(load_params(scope, cfg), cfg)
        return GenerationServer(model, **server_kwargs)

    return spawn
