"""Self-healing fleet supervision: watchdog, resurrection, quarantine.

The fleet front door (serving/router.py) survives a replica death only
by SHRINKING: killed replicas never come back, a replica that *hangs*
(stuck engine iteration — no death, no failed future, nothing raises)
is never detected, and a request whose replay deterministically faults
the engine is re-admitted on survivor after survivor until the whole
fleet is gone. This module is the missing control loop, the TPU-way
re-expression of Fluid 1.5 pserver-mode fault-tolerant serving
(SURVEY §1): a fleet is defined by what it survives.

Three mechanisms, one heartbeat (``FleetSupervisor.on_heartbeat``,
driven by ``FleetRouter.step()`` — so the whole tier runs under the
injected serving clock, with zero wall-clock dependence):

- **Watchdog** — per-replica progress marks (``Replica.progress_mark``:
  scheduler iteration + token/admission counters, pure counter reads).
  A replica with work whose mark is frozen for ``hang_heartbeats``
  consecutive heartbeats is declared HUNG: torn down like a death
  (its stream registrations drain — the dead engine is never pumped
  again, so no late token can reach a client), its in-flight requests
  re-admitted bitwise on survivors by the router's failover path. A
  replica whose pumps run but take longer than ``slow_ms`` is labeled
  ``slow`` (surfaced in health/stats — an operator signal, not a
  teardown: slow is a capacity problem, hung is a correctness one).
  Dead replicas (kill, engine fault) are picked up the same heartbeat.

- **Resurrection** — a failed replica slot is respawned through
  ``spawn_fn(index)`` under a crash-loop circuit breaker: exponential
  backoff measured in heartbeats (``backoff_heartbeats`` ·
  ``backoff_factor``^failures), a half-open PROBE request served
  end-to-end before the replica rejoins rotation, and permanent
  eviction after ``max_crash_loops`` consecutive failures (a failed
  spawn/probe, or a resurrected replica dying again before retiring a
  single request). ``make_checkpoint_spawn`` builds a spawn_fn that
  reloads weights through robustness/checkpoint_manager.py (newest
  valid checkpoint, CRC-validated, walking back past corrupt ones).
  Before rejoining, the new replica's prefix cache is RE-WARMED from
  the router's fleet-wide ``ChunkPopularityDigest`` — the most popular
  prompt chains re-prefill into its index, so a resurrected replica
  rejoins near its pre-death hit rate instead of cold.

- **Quarantine** (lives in the router; this module owns the error) —
  the router tracks per-request failover lineage; a request implicated
  in ``poison_threshold`` (default 2) replica deaths is failed with a
  structured ``PoisonRequestError`` and recorded in the fleet flight
  recorder instead of cascading onward.

Everything is deterministic: heartbeats are router iterations, backoff
is heartbeat counts, probes and warm-ups pump manual-drive engines
synchronously. docs/robustness.md "Self-healing fleet" has the tuning
guide; metrics are ``serving.fleet.{hangs,resurrections,crash_loops,
quarantines}``.
"""

import numpy as np

__all__ = ["FleetSupervisor", "SupervisorConfig", "PoisonRequestError",
           "ChunkPopularityDigest", "make_checkpoint_spawn"]


class PoisonRequestError(RuntimeError):
    """The router quarantined this request: its failover lineage
    implicates it in `deaths` replica deaths (engine faults naming its
    lane), so re-admitting it again would predictably kill another
    replica. `lineage` lists the deaths it was present for (replica
    name + kind), `attempts` the failovers it consumed, `flight_dump`
    the fleet flight-recorder artifact written at quarantine time."""

    def __init__(self, message, request_id, lineage, attempts,
                 flight_dump=None):
        super().__init__(message)
        self.request_id = request_id
        self.lineage = list(lineage)
        self.deaths = sum(1 for d in self.lineage if d.get("implicated"))
        self.attempts = attempts
        self.flight_dump = flight_dump


class ChunkPopularityDigest:
    """Fleet-wide chunk popularity, kept by the router: chain key ->
    (parent key, chunk tokens, hit count). Fed on every submit from
    the prompt's chain keys (the same blake2b chain the per-replica
    prefix indexes use), consumed by resurrection to re-warm a fresh
    replica's cache with the prompts the FLEET actually serves —
    popularity survives any single replica's death because it lives
    here, not in the dead index.

    Bounded: past `max_entries` the least-popular entries are dropped;
    a chain whose ancestor was dropped reconstructs to None and is
    skipped at warm time (re-warm is best-effort by design)."""

    def __init__(self, max_entries=512):
        self.max_entries = int(max_entries)
        self._entries = {}          # key -> [parent, tokens, hits]

    def observe(self, keys, prompt, block_size):
        """Record one routed prompt's full chunks."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        parent = None
        for i, key in enumerate(keys):
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = [
                    parent, np.array(
                        prompt[i * block_size:(i + 1) * block_size],
                        np.int32, copy=True), 1]
            else:
                e[2] += 1
            parent = key
        if len(self._entries) > self.max_entries:
            self._shrink()

    def _shrink(self):
        keep = sorted(self._entries.items(),
                      key=lambda kv: kv[1][2],
                      reverse=True)[:self.max_entries]
        self._entries = dict(keep)

    def forget(self, keys):
        """Drop these chain keys (the router calls this when a request
        is QUARANTINED: re-warming a resurrected replica with the very
        prompt whose replay faults engines would re-enter the cascade
        through the healing path). Descendants that survive reconstruct
        to None once an ancestor is gone and are skipped at warm
        time."""
        for key in keys:
            self._entries.pop(key, None)

    def prompt_for(self, key):
        """Reconstruct the full token prefix ending at chain `key` by
        walking parents to the root; None when the chain is broken
        (an ancestor was shrunk away)."""
        chunks = []
        while key is not None:
            e = self._entries.get(key)
            if e is None:
                return None
            chunks.append(e[1])
            key = e[0]
        return np.concatenate(list(reversed(chunks)))

    def top_chains(self, n):
        """The `n` most popular chain endpoints, deepest-first within
        a chain (a taken key covers all its ancestors, so they are
        skipped — warming the deepest chunk warms the whole prefix)."""
        parents = {e[0] for e in self._entries.values()
                   if e[0] is not None}
        ranked = sorted(self._entries.items(),
                        key=lambda kv: (kv[1][2], kv[0]), reverse=True)
        taken, covered = [], set()
        for key, _e in ranked:
            if key in covered:
                continue
            # prefer leaves; an interior key only if no descendant of
            # it was (or will be) taken — approximated by skipping
            # keys that are parents of a live entry with >= its hits
            if key in parents:
                continue
            taken.append(key)
            walk = key
            while walk is not None:
                covered.add(walk)
                walk = self._entries[walk][0] \
                    if walk in self._entries else None
            if len(taken) >= n:
                break
        if len(taken) < n:
            # chains may be all-interior after a shrink: top up with
            # the most popular uncovered keys of any shape
            for key, _e in ranked:
                if key not in covered:
                    taken.append(key)
                    covered.add(key)
                    if len(taken) >= n:
                        break
        return taken

    def __len__(self):
        return len(self._entries)

    def stats(self):
        return {"entries": len(self._entries),
                "max_entries": self.max_entries}


class SupervisorConfig:
    """Tuning knobs for FleetSupervisor (docs/robustness.md
    "Self-healing fleet" walks through each):

    - hang_heartbeats: consecutive no-progress heartbeats (with work
      pending) before a replica is declared hung and torn down.
    - slow_ms: pump-time EMA above which a replica is labeled `slow`
      (None disables the classification).
    - resurrect: respawn failed replicas (needs the router's spawn_fn).
    - backoff_heartbeats / backoff_factor: crash-loop circuit breaker
      delay = backoff_heartbeats * backoff_factor**failures, in
      heartbeats (deterministic under injected clocks).
    - max_crash_loops: consecutive failed resurrections (or
      die-before-serving relapses) before the slot is PERMANENTLY
      evicted.
    - probe_tokens / probe_timeout_s: the half-open probe request a
      respawned engine must serve end-to-end before rejoining.
    - warm_chains: how many popular prompt chains to re-prefill into
      the resurrected replica's prefix cache (0 disables re-warm).
    """

    def __init__(self, hang_heartbeats=3, slow_ms=None, resurrect=True,
                 backoff_heartbeats=2, backoff_factor=2.0,
                 max_crash_loops=3, probe_tokens=2, probe_timeout_s=30.0,
                 warm_chains=8):
        if hang_heartbeats < 1:
            raise ValueError("hang_heartbeats must be >= 1")
        if max_crash_loops < 1:
            raise ValueError("max_crash_loops must be >= 1")
        self.hang_heartbeats = int(hang_heartbeats)
        self.slow_ms = None if slow_ms is None else float(slow_ms)
        self.resurrect = bool(resurrect)
        self.backoff_heartbeats = int(backoff_heartbeats)
        self.backoff_factor = float(backoff_factor)
        self.max_crash_loops = int(max_crash_loops)
        self.probe_tokens = int(probe_tokens)
        self.probe_timeout_s = float(probe_timeout_s)
        self.warm_chains = int(warm_chains)


class FleetSupervisor:
    """The router's self-healing control loop. Constructed by
    FleetRouter (``supervisor=True`` / ``SupervisorConfig(...)``) and
    driven by its step(): one on_heartbeat() per router iteration —
    plus idle ticks while any duty (a pending resurrection backoff)
    remains, so manual-drive ``run_until_idle`` keeps pumping until
    the fleet is back at strength."""

    def __init__(self, router, config=None):
        self.router = router
        self.config = config or SupervisorConfig()
        self.heartbeat = 0
        self._watch = {}        # index -> [last mark, stale count]
        self._breaker = {}      # index -> breaker dict
        self.counts = {"hangs": 0, "resurrections": 0, "crash_loops": 0,
                       "evictions": 0, "slow_flags": 0, "probes": 0,
                       "warm_prompts": 0}

    # -- heartbeat ---------------------------------------------------------
    def on_heartbeat(self):
        """One supervision pass over every replica slot. Returns True
        when the supervisor acted or still owes work (a backoff timer
        pending) — the router's step() treats that as fleet activity."""
        self.heartbeat += 1
        did = False
        for r in list(self.router._replicas):
            if r.state in ("evicted", "drained"):
                continue
            if not r.alive():
                did = self._supervise_dead(r) or did
                continue
            # a previously-resurrected replica that has now served
            # CLIENT traffic (retired anything beyond its own probe +
            # warm-up requests — the adoption baseline) closes its
            # crash-loop window
            b = self._breaker.get(r.index)
            if b and b["failures"] and r.generation > 0 and \
                    r.server._sched.counts["retired"] > \
                    b.get("adopted_retired", 0):
                b["failures"] = 0
            did = self._watchdog(r) or did
        return did

    def _watchdog(self, r):
        cfg = self.config
        mark = r.progress_mark()
        w = self._watch.setdefault(r.index, [mark, 0])
        if r.server._sched.has_work() and mark == w[0]:
            w[1] += 1
            if w[1] >= cfg.hang_heartbeats:
                w[0], w[1] = mark, 0
                self.counts["hangs"] += 1
                self.router._declare_hung(r.index)
                return True
        else:
            w[0], w[1] = mark, 0
        if cfg.slow_ms is not None and r.step_ms_ema is not None:
            verdict = "slow" if r.step_ms_ema > cfg.slow_ms else "ok"
            if verdict == "slow" and r.condition != "slow":
                self.counts["slow_flags"] += 1
            r.condition = verdict
        return False

    # -- resurrection ------------------------------------------------------
    def _supervise_dead(self, r):
        if not self.config.resurrect or self.router.spawn_fn is None:
            return False
        b = self._breaker.get(r.index)
        if b is None:
            b = {"failures": 0, "retry_at": 0, "relapse_gen": None}
            self._breaker[r.index] = b
        # a resurrected replica that died again before retiring a
        # single CLIENT request (beyond its own probe/warm-up — the
        # adoption baseline) is a crash-loop relapse — count it once
        # per generation, toward permanent eviction
        if r.generation > 0 and b["relapse_gen"] != r.generation and \
                r.server._sched.counts["retired"] <= \
                b.get("adopted_retired", 0):
            b["relapse_gen"] = r.generation
            self._crash_loop(r, b, "died before serving any request")
            if r.state == "evicted":
                return False
        if b["retry_at"] == 0:
            b["retry_at"] = self.heartbeat + self._backoff(b["failures"])
            return True
        if self.heartbeat < b["retry_at"]:
            return True     # duty pending: keep the router stepping
        b["retry_at"] = 0
        self._attempt_resurrection(r, b)
        return True

    def _backoff(self, failures):
        return max(1, round(self.config.backoff_heartbeats
                            * self.config.backoff_factor ** failures))

    def _crash_loop(self, r, b, why):
        b["failures"] += 1
        self.counts["crash_loops"] += 1
        self.router._count_fleet("crash_loops")
        self.router._flight_event(
            "crash_loop", replica=r.name, failures=b["failures"],
            why=why)
        if b["failures"] >= self.config.max_crash_loops:
            r.state = "evicted"
            self.counts["evictions"] += 1
            self.router._flight_event(
                "replica_evicted", replica=r.name,
                crash_loops=b["failures"])
            self.router._publish_gauges()

    def _attempt_resurrection(self, r, b):
        router = self.router
        try:
            server = router.spawn_fn(r.index)
        except Exception as e:      # noqa: BLE001 — spawn is user code
            self._crash_loop(r, b, f"spawn_fn raised: {e!r}")
            return
        try:
            if server.block_size != router._block_size:
                raise ValueError(
                    f"spawn_fn returned block_size "
                    f"{server.block_size}, fleet uses "
                    f"{router._block_size} (affinity keys chunk by it)")
            self._probe(server)
            self._warm(server)
        except Exception as e:      # noqa: BLE001 — half-open probe:
            #                         ANY failure re-opens the breaker
            try:
                server.close(drain=False)
            except Exception:       # noqa: BLE001
                pass
            self._crash_loop(r, b, f"probe/warm failed: {e!r}")
            return
        router._adopt_replica(r.index, server,
                              generation=r.generation + 1)
        self._watch.pop(r.index, None)
        b["relapse_gen"] = None
        # relapse baseline: retirements at adoption are the probe +
        # warm-ups, not client traffic
        b["adopted_retired"] = server._sched.counts["retired"]
        self.counts["resurrections"] += 1
        router._count_fleet("resurrections")
        router._flight_event(
            "resurrection", replica=r.name,
            generation=r.generation + 1,
            warmed_chains=min(self.config.warm_chains,
                              len(router._digest)))

    def _probe(self, server):
        """Half-open probe: the respawned engine must serve ONE real
        request end-to-end before any client traffic routes to it."""
        cfg = self.config
        prompt = self._probe_prompt()
        fut = server.submit(prompt, max_new_tokens=cfg.probe_tokens)
        self._pump(server, [fut])
        fut.result(timeout=cfg.probe_timeout_s)
        self.counts["probes"] += 1

    def _probe_prompt(self):
        """The most popular cached chain doubles as the probe payload
        (it exercises the exact path production traffic will); with an
        empty digest, a minimal token-0 prompt."""
        digest = self.router._digest
        for key in digest.top_chains(1):
            p = digest.prompt_for(key)
            if p is not None:
                return p
        return np.zeros(2, np.int32)

    def _warm(self, server):
        """Prefix re-warm: re-prefill the fleet's most popular prompt
        chains into the fresh replica's index BEFORE it rejoins, so
        affinity routing finds it warm. Best-effort: a chain that no
        longer fits (or was shrunk out of the digest) is skipped."""
        cfg = self.config
        if cfg.warm_chains <= 0 or server._prefix is None:
            return
        futs = []
        for key in self.router._digest.top_chains(cfg.warm_chains):
            prompt = self.router._digest.prompt_for(key)
            if prompt is None:
                continue
            try:
                futs.append(server.submit(prompt, max_new_tokens=1))
            except (ValueError, RuntimeError):
                continue    # too big for this pool / raced a close
        self._pump(server, futs)
        done = 0
        for f in futs:
            f.result(timeout=cfg.probe_timeout_s)
            done += 1
        self.counts["warm_prompts"] += done

    @staticmethod
    def _pump(server, futs):
        """Drive a not-yet-adopted server: manual-drive engines are
        pumped synchronously (deterministic tier); worker-threaded
        engines drain on their own and the caller waits on futures."""
        if server._worker is None:
            server.run_until_idle()

    def stats(self):
        return {
            "heartbeat": self.heartbeat,
            "config": {
                "hang_heartbeats": self.config.hang_heartbeats,
                "slow_ms": self.config.slow_ms,
                "max_crash_loops": self.config.max_crash_loops,
                "backoff_heartbeats": self.config.backoff_heartbeats,
                "warm_chains": self.config.warm_chains,
            },
            "breaker": {
                i: {"failures": b["failures"],
                    "retry_at_heartbeat": b["retry_at"] or None}
                for i, b in self._breaker.items()},
            **dict(self.counts),
        }


def make_checkpoint_spawn(manager, cfg, scope_factory=None,
                          executor=None, **server_kwargs):
    """Build a resurrection ``spawn_fn`` that reloads model weights
    through a robustness.CheckpointManager: each call restores the
    newest VALID checkpoint (CRC-validated, walking back past corrupt
    candidates) into a fresh scope and builds a GenerationServer over
    it — the serving twin of GuardedTrainer's rollback-restore.

        manager = CheckpointManager(root, program=main_program)
        router = FleetRouter(servers, spawn_fn=make_checkpoint_spawn(
            manager, gpt_cfg, num_slots=4, start=False),
            supervisor=True)

    `server_kwargs` pass through to GenerationServer (start=False for
    manual-drive fleets). Raises CheckpointError/CheckpointCorruptError
    when no loadable checkpoint exists — the supervisor counts that as
    a crash-loop, exactly like a failed spawn."""
    from ..core.executor import Executor, Scope
    from ..models.gpt import load_params
    from ..serving.engine import GenerationServer, GPTServingModel
    from .checkpoint_manager import CheckpointError

    def spawn(index):
        scope = (scope_factory or Scope)()
        exe = executor if executor is not None else Executor()
        meta = manager.restore(exe, scope=scope,
                               restore_step_counter=False)
        if meta is None:
            raise CheckpointError(
                f"resurrection of replica {index}: no checkpoint "
                f"under {manager.root}")
        model = GPTServingModel(load_params(scope, cfg), cfg)
        return GenerationServer(model, **server_kwargs)

    return spawn
