"""Preemption-safe shutdown: SIGTERM/SIGINT -> drain -> emergency save.

Cloud TPU preemption is a SIGTERM with a grace window; a training loop
with up to `async_window` steps in flight must NOT checkpoint from the
signal handler (the scope may be mid-update and most of the runtime is
not async-signal-safe). The handler here only sets a flag; the
GuardedTrainer polls it between dispatches, drains the async window,
writes an emergency checkpoint through the normal atomic path, and
returns cleanly. The chaos tier requests preemption through the same
flag, so both paths are one code path.
"""

import signal
import threading

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """Install with `install()` (or use as a context manager); poll
    `requested()` from the training loop. Re-entrant signals are
    harmless (the flag is already set); a second SIGINT restores the
    previous handler so a stuck drain can still be interrupted."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._old = {}
        self._installed = False

    # -- flag ----------------------------------------------------------
    def request(self, signum=None, frame=None):
        """The signal handler body: flag only, no I/O, no locks."""
        self._flag.set()
        if signum == signal.SIGINT and self._installed:
            # let a second ^C interrupt a wedged drain/save
            old = self._old.get(signal.SIGINT)
            if old is not None:
                signal.signal(signal.SIGINT, old)

    def requested(self):
        return self._flag.is_set()

    def clear(self):
        self._flag.clear()

    # -- install -------------------------------------------------------
    def install(self):
        """Install on the main thread; a no-op elsewhere (python only
        delivers signals to the main thread anyway)."""
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.signals:
            self._old[sig] = signal.signal(sig, self.request)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
