"""Light-NAS: search space + simulated-annealing controller + strategy.

Parity: the reference's `contrib/slim/nas/` — SearchSpace
(search_space.py:19), LightNASStrategy (light_nas_strategy.py:35),
ControllerServer/SearchAgent (controller_server.py:24, search_agent.py:21) —
and `contrib/slim/searcher/controller.py:59` (SAController).

TPU-native design: a candidate is a *token vector*; `create_net` builds a
fresh Program for it and evaluation runs through the whole-program-jit
Executor, so each candidate is ONE XLA executable on tiny eval shapes.
The FLOPs constraint is checked symbolically with
`utils.model_stat.count_flops` on the un-compiled Program, so infeasible
candidates are rejected before any compile. The controller itself is plain
host Python (search is control-plane work, not MXU work); the distributed
search uses the same line-protocol TCP server/agent pair as the reference
so multiple hosts can pull tokens from one annealing chain.
"""

import math
import socket
import threading

import numpy as np

from ..utils.model_stat import count_flops
from ..utils.log import get_logger

_logger = get_logger(__name__)

__all__ = [
    "SearchSpace", "EvolutionaryController", "SAController",
    "ControllerServer", "SearchAgent", "LightNASStrategy",
]


class SearchSpace:
    """Abstract search space (ref search_space.py:19).

    Subclasses define the token domain and how tokens become a network.
    """

    def init_tokens(self):
        """Initial token vector (list of ints)."""
        raise NotImplementedError("Abstract method.")

    def range_table(self):
        """Per-position exclusive upper bounds: tokens[i] in [0, table[i])."""
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens):
        """Build programs for `tokens`.

        Returns (startup_program, train_program, eval_program,
        train_fetches, eval_fetches) like the reference contract; strategies
        only require what their eval_fn consumes, so lighter tuples are fine
        when used with a custom eval_fn.
        """
        raise NotImplementedError("Abstract method.")

    def get_model_latency(self, program):
        """Proxy latency of a candidate: forward FLOPs of its Program.

        The reference queries a latency lookup table; on TPU the symbolic
        FLOP count is the compile-free proxy (MXU-bound nets are
        FLOPs-proportional at fixed shapes). Override for a real table.
        """
        total, _ = count_flops(program)
        return total


class EvolutionaryController:
    """Abstract controller (ref controller.py:28)."""

    def update(self, tokens, reward):
        raise NotImplementedError("Abstract method.")

    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError("Abstract method.")

    def next_tokens(self):
        raise NotImplementedError("Abstract method.")


class SAController(EvolutionaryController):
    """Simulated-annealing controller (ref controller.py:59).

    Accepts a worse candidate with probability exp(dR / T), T decaying
    geometrically per update. Seeded: searches are replayable
    (utils/determinism story applies to the search loop too).
    """

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_try_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_try_number = max_try_number
        self._rng = np.random.default_rng(seed)
        self._constrain_func = None
        self._tokens = None
        self._reward = -float("inf")
        self._best_tokens = None
        self._max_reward = -float("inf")
        self._iter = 0

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        self._iter += 1
        temperature = self._init_temperature * self._reduce_rate ** self._iter
        dr = reward - self._reward
        if dr > 0 or self._rng.random() <= math.exp(
                min(0.0, dr) / max(temperature, 1e-12)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)
        _logger.info("SA iter %d: reward=%.6g best=%.6g tokens=%s",
                     self._iter, reward, self._max_reward, self._best_tokens)

    def _mutate(self, tokens):
        new_tokens = list(tokens)
        index = int(self._rng.integers(len(self._range_table)))
        span = self._range_table[index]
        if span > 1:
            new_tokens[index] = int(
                (new_tokens[index] + self._rng.integers(1, span)) % span)
        return new_tokens

    def next_tokens(self):
        new_tokens = self._mutate(self._tokens)
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_try_number):
            if self._constrain_func(new_tokens):
                return new_tokens
            new_tokens = self._mutate(self._tokens)
        return list(self._tokens)  # no feasible neighbour found


class ControllerServer:
    """TCP server sharing one controller across search workers
    (ref controller_server.py:24).

    Line protocol, one request per connection:
      ``next_tokens\\n``            -> ``t0,t1,...\\n``
      ``update <reward> t0,t1,...`` -> ``ok\\n``
    """

    def __init__(self, controller, address=("127.0.0.1", 0), key="light-nas"):
        self._controller = controller
        self._key = key
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(16)
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True)

    def start(self):
        self._thread.start()
        return self

    @property
    def address(self):
        return self._sock.getsockname()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                line = conn.makefile("r").readline().strip()
                with self._lock:
                    if line == "next_tokens":
                        toks = self._controller.next_tokens()
                        conn.sendall(
                            (",".join(map(str, toks)) + "\n").encode())
                    elif line.startswith("update "):
                        _, reward, toks = line.split(" ", 2)
                        self._controller.update(
                            [int(t) for t in toks.split(",")], float(reward))
                        conn.sendall(b"ok\n")
                    else:
                        conn.sendall(b"err\n")
            except Exception:
                pass
            finally:
                conn.close()

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class SearchAgent:
    """Client for ControllerServer (ref search_agent.py:21)."""

    def __init__(self, server_ip, server_port):
        self._addr = (server_ip, server_port)

    def _request(self, line):
        with socket.create_connection(self._addr, timeout=10) as conn:
            conn.sendall((line + "\n").encode())
            return conn.makefile("r").readline().strip()

    def next_tokens(self):
        return [int(t) for t in self._request("next_tokens").split(",")]

    def update(self, tokens, reward):
        return self._request(
            "update %s %s" % (reward, ",".join(map(str, tokens)))) == "ok"


class LightNASStrategy:
    """Search driver (ref light_nas_strategy.py:35).

    Repeatedly: draw tokens from the controller (or a remote agent),
    reject candidates over `target_flops`/`target_latency` symbolically,
    evaluate the survivor with `eval_fn(tokens, search_space)` -> reward,
    and anneal. Returns the best (tokens, reward).
    """

    def __init__(self, search_space, controller=None, eval_fn=None,
                 target_flops=None, target_latency=None, search_steps=10,
                 server_ip=None, server_port=0, is_server=False,
                 key="light-nas"):
        self._space = search_space
        self._controller = controller or SAController()
        self._eval_fn = eval_fn
        self._target_flops = target_flops
        self._target_latency = target_latency
        self._search_steps = search_steps
        self._server = None
        self._agent = None
        if is_server:
            self._server = ControllerServer(
                self._controller, ("127.0.0.1", server_port), key).start()
        if server_ip:
            if not server_port and self._server is None:
                raise ValueError("server_port is required when connecting "
                                 "to a remote controller server")
            self._agent = SearchAgent(server_ip,
                                      server_port or self._server.address[1])

    def _feasible(self, tokens):
        if self._target_flops is None and self._target_latency is None:
            return True
        net = self._space.create_net(tokens)
        train_prog = net[1] if isinstance(net, tuple) else net
        lat = self._space.get_model_latency(train_prog)
        if self._target_flops is not None and lat > self._target_flops:
            return False
        if self._target_latency is not None and lat > self._target_latency:
            return False
        return True

    def search(self):
        init = self._space.init_tokens()
        self._controller.reset(self._space.range_table(), init,
                               constrain_func=self._feasible)
        history = []
        tokens = list(init)
        for _ in range(self._search_steps):
            reward = self._evaluate(tokens)
            history.append((list(tokens), reward))
            if self._agent is not None:
                self._agent.update(tokens, reward)
                tokens = self._agent.next_tokens()
            else:
                self._controller.update(tokens, reward)
                tokens = self._controller.next_tokens()
        best_tokens, best_reward = max(history, key=lambda h: h[1])
        self.history = history
        return best_tokens, best_reward

    def _evaluate(self, tokens):
        if self._eval_fn is not None:
            return float(self._eval_fn(tokens, self._space))
        raise ValueError("LightNASStrategy needs an eval_fn "
                         "(tokens, search_space) -> reward")

    def close(self):
        if self._server is not None:
            self._server.close()
