"""Slim-lite: pruning masks + distillation losses + light-NAS search.

Parity: the reference's contrib/slim (PruneStrategy, distillation
losses, nas/light_nas_strategy + searcher/controller). See prune.py,
distill.py, nas.py.
"""

from .prune import Pruner, sensitivity_prune_ratios  # noqa: F401
from .distill import (soft_label_loss, l2_hint_loss, fsp_loss)  # noqa: F401
from .nas import (SearchSpace, EvolutionaryController, SAController,  # noqa: F401
                  ControllerServer, SearchAgent, LightNASStrategy)
