"""Slim-lite: pruning masks + distillation losses.

Parity: the reference's contrib/slim (PruneStrategy / distillation
distill losses). See prune.py and distill.py.
"""

from .prune import Pruner, sensitivity_prune_ratios  # noqa: F401
from .distill import (soft_label_loss, l2_hint_loss, fsp_loss)  # noqa: F401
