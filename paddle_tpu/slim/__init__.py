"""Slim: pruning + distillation + quantization strategies + light-NAS.

Parity: the reference's contrib/slim — both the functional core
(prune.py, distill.py, nas.py) and the reference's class surface
(core.py Compressor/Strategy/ConfigFactory, graph.py GraphWrapper,
strategy classes, quantization passes re-exported from quant/).
"""

from .prune import (Pruner, sensitivity_prune_ratios,  # noqa: F401
                    StructurePruner, PruneStrategy, UniformPruneStrategy,
                    SensitivePruneStrategy, AutoPruneStrategy)
from .distill import (soft_label_loss, l2_hint_loss, fsp_loss,  # noqa: F401
                      merge_programs, L2Distiller, SoftLabelDistiller,
                      FSPDistiller, DistillationStrategy)
from .nas import (SearchSpace, EvolutionaryController, SAController,  # noqa: F401
                  ControllerServer, SearchAgent, LightNASStrategy)
from .core import (Context, Strategy, Compressor,  # noqa: F401
                   ConfigFactory)
from .graph import (GraphWrapper, VarWrapper, OpWrapper,  # noqa: F401
                    SlimGraphExecutor)
from ..quant.passes import (  # noqa: F401
    QuantizationTransformPass, QuantizationFreezePass, ConvertToInt8Pass,
    TransformForMobilePass, ScaleForTrainingPass, ScaleForInferencePass,
    AddQuantDequantPass, QuantizationStrategy,
    MKLDNNPostTrainingQuantStrategy, TransformForMkldnnPass)
