"""Graph views for slim strategies.

Parity: python/paddle/fluid/contrib/slim/graph/graph_wrapper.py:26
(GraphWrapper/VarWrapper/OpWrapper) and graph/executor.py:21
(SlimGraphExecutor).

The reference wraps the C++ IrGraph; here the Program IS a python op
list, so the wrappers are thin stable views the strategies share —
same traversal API (ops/vars/pre_ops/next_ops), no graph copy.
"""

from ..core.framework import Parameter

__all__ = ["GraphWrapper", "VarWrapper", "OpWrapper", "SlimGraphExecutor"]


class VarWrapper:
    def __init__(self, var, graph):
        self._var = var
        self._graph = graph

    def name(self):
        return self._var.name

    def shape(self):
        return tuple(self._var.shape or ())

    def is_parameter(self):
        return isinstance(self._var, Parameter)

    def set_shape(self, shape):
        self._var.shape = tuple(shape)

    def __eq__(self, other):
        return isinstance(other, VarWrapper) and \
            self._var.name == other._var.name

    def __hash__(self):
        return hash(self._var.name)

    def __repr__(self):
        return f"VarWrapper({self._var.name})"


class OpWrapper:
    def __init__(self, op, graph):
        self._op = op
        self._graph = graph

    def type(self):
        return self._op.type

    def idx(self):
        return self._graph.ops().index(self)

    def attr(self, name):
        return self._op.attr(name)

    def set_attr(self, name, value):
        self._op._set_attr(name, value)

    def inputs(self, slot=None):
        names = (self._op.input_names if slot is None
                 else self._op.input(slot))
        return [self._graph.var(n) for n in names
                if self._graph.var(n) is not None]

    def outputs(self, slot=None):
        names = (self._op.output_names if slot is None
                 else self._op.output(slot))
        return [self._graph.var(n) for n in names
                if self._graph.var(n) is not None]

    def __eq__(self, other):
        return isinstance(other, OpWrapper) and self._op is other._op

    def __hash__(self):
        return id(self._op)

    def __repr__(self):
        return f"OpWrapper({self._op.type})"


class GraphWrapper:
    """in_nodes/out_nodes: {logical_name: var_name} like the reference
    (feed targets and fetch targets of the wrapped program)."""

    def __init__(self, program, in_nodes=None, out_nodes=None):
        self.program = program
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})

    def _block(self):
        return self.program.global_block()

    def all_parameters(self):
        return [VarWrapper(v, self) for v in self._block().vars.values()
                if isinstance(v, Parameter)]

    def vars(self):
        return [VarWrapper(v, self) for v in self._block().vars.values()]

    def var(self, name):
        v = self._block().vars.get(name)
        return VarWrapper(v, self) if v is not None else None

    def ops(self):
        return [OpWrapper(op, self) for op in self._block().ops]

    def pre_ops(self, op):
        ins = {v.name() for v in op.inputs()}
        return [o for o in self.ops()
                if ins & {v.name() for v in o.outputs()}]

    def next_ops(self, op):
        outs = {v.name() for v in op.outputs()}
        return [o for o in self.ops()
                if outs & {v.name() for v in o.inputs()}]

    def numel_params(self):
        total = 0
        for p in self.all_parameters():
            n = 1
            for s in p.shape():
                n *= max(int(s), 1)
            total += n
        return total

    def clone(self, for_test=False):
        return GraphWrapper(self.program.clone(for_test=for_test),
                            self.in_nodes, self.out_nodes)


class SlimGraphExecutor:
    """Runs a GraphWrapper through the normal Executor
    (ref graph/executor.py — same run contract, XLA underneath)."""

    def __init__(self, place=None):
        from ..core.executor import Executor
        self.exe = Executor(place)

    def run(self, graph, scope, data=None):
        from ..core.executor import scope_guard
        feed = data if isinstance(data, dict) else None
        fetch_list = [graph.out_nodes[k] for k in sorted(graph.out_nodes)]
        with scope_guard(scope):
            return self.exe.run(graph.program, feed=feed,
                                fetch_list=fetch_list)
