"""Slim compression pipeline: Context / Strategy / Compressor / config.

Parity: python/paddle/fluid/contrib/slim/core/ (compressor.py:30 Context,
compressor.py:135 Compressor, strategy.py:20 Strategy, config.py:29
ConfigFactory).

Same user contract as the reference — yaml-configured strategy pipeline
driven epoch by epoch over a train program — with the executor being one
whole-program XLA step underneath. Checkpointing rides io/checkpoint.py
instead of per-strategy pickles.
"""

import importlib
import logging

import numpy as np

from ..core.executor import Executor, Scope, global_scope, scope_guard
from .graph import GraphWrapper, SlimGraphExecutor  # noqa: F401

__all__ = ["Context", "Strategy", "Compressor", "ConfigFactory"]

_logger = logging.getLogger("slim")


class Strategy:
    """Epoch-ranged hook bundle (ref strategy.py:20): subclasses override
    any of the on_* hooks; the compressor fires them in order."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass


class Context:
    """Mutable pipeline state threaded through strategy hooks
    (ref compressor.py:30)."""

    def __init__(self, place=None, scope=None, train_graph=None,
                 eval_graph=None, optimize_graph=None, epoch_id=0,
                 batch_id=0, eval_reader=None, eval_feed_list=None,
                 teacher_graphs=None, train_optimizer=None,
                 distiller_optimizer=None):
        self.place = place
        self.scope = scope if scope is not None else global_scope()
        self.train_graph = train_graph
        self.eval_graph = eval_graph
        self.optimize_graph = optimize_graph
        self.epoch_id = epoch_id
        self.batch_id = batch_id
        self.eval_reader = eval_reader
        self.eval_feed_list = list(eval_feed_list or [])
        self.teacher_graphs = teacher_graphs or []
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.eval_results = {}
        self.k_v = {}

    def put(self, key, value):
        self.k_v[key] = value

    def get(self, key):
        return self.k_v.get(key)

    def eval_result_tail(self, metric):
        r = self.eval_results.get(metric)
        return r[-1] if r else None


class Compressor:
    """Epoch driver (ref compressor.py:135): builds a Context, fires
    strategy hooks around a plain train loop, evaluates per epoch.

    feed lists are var NAMES (our feed contract); fetch lists are vars
    or names. Strategies come from __init__ or from config() yaml.
    """

    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list=None, train_fetch_list=None,
                 eval_program=None, eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, teacher_programs=(),
                 checkpoint_path=None, train_optimizer=None,
                 distiller_optimizer=None, epoch=1, strategies=None):
        self.place = place
        self.scope = scope if scope is not None else Scope()
        self.train_graph = GraphWrapper(
            train_program,
            out_nodes={i: n for i, n in
                       enumerate(_names(train_fetch_list))})
        self.eval_graph = (GraphWrapper(
            eval_program,
            out_nodes={i: n for i, n in enumerate(_names(eval_fetch_list))})
            if eval_program is not None else None)
        self.train_reader = train_reader
        self.train_feed_list = list(train_feed_list or [])
        self.train_fetch_list = _names(train_fetch_list)
        self.eval_reader = eval_reader
        self.eval_feed_list = list(eval_feed_list or [])
        self.eval_fetch_list = _names(eval_fetch_list)
        self.teacher_graphs = [GraphWrapper(p) for p in teacher_programs]
        self.checkpoint_path = checkpoint_path
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.epoch = epoch
        self.strategies = list(strategies or [])

    def config(self, config_file):
        """Load strategies + epoch from a yaml config
        (ref compressor.py config(); format: ConfigFactory)."""
        factory = ConfigFactory(config_file)
        comp = factory.compressor
        self.epoch = int(comp.get("epoch", self.epoch))
        if comp.get("checkpoint_path"):
            self.checkpoint_path = comp["checkpoint_path"]
        self.strategies.extend(factory.instance(name)
                               for name in comp.get("strategies", []))
        return self

    def run(self):
        exe = Executor(self.place)
        context = Context(place=self.place, scope=self.scope,
                          train_graph=self.train_graph,
                          eval_graph=self.eval_graph,
                          eval_reader=self.eval_reader,
                          eval_feed_list=self.eval_feed_list,
                          teacher_graphs=self.teacher_graphs,
                          train_optimizer=self.train_optimizer,
                          distiller_optimizer=self.distiller_optimizer)
        for s in self.strategies:
            s.on_compression_begin(context)
        for epoch_id in range(self.epoch):
            context.epoch_id = epoch_id
            active = [s for s in self.strategies
                      if s.start_epoch <= epoch_id <= max(s.end_epoch,
                                                          s.start_epoch)]
            for s in active:
                s.on_epoch_begin(context)
            if self.train_reader is not None:
                for batch_id, data in enumerate(self.train_reader()):
                    context.batch_id = batch_id
                    for s in active:
                        s.on_batch_begin(context)
                    feed = dict(zip(self.train_feed_list, data)) \
                        if not isinstance(data, dict) else data
                    with scope_guard(self.scope):
                        exe.run(context.train_graph.program, feed=feed,
                                fetch_list=self.train_fetch_list)
                    for s in active:
                        s.on_batch_end(context)
            for s in active:
                s.on_epoch_end(context)
            self._eval(exe, context)
        for s in self.strategies:
            s.on_compression_end(context)
        return context

    def _eval(self, exe, context):
        if self.eval_graph is None or self.eval_reader is None:
            return
        results = []
        for data in self.eval_reader():
            feed = dict(zip(self.eval_feed_list, data)) \
                if not isinstance(data, dict) else data
            with scope_guard(self.scope):
                out = exe.run(self.eval_graph.program, feed=feed,
                              fetch_list=self.eval_fetch_list)
            results.append([float(np.asarray(v).reshape(-1)[0])
                            for v in out])
        if results:
            means = np.mean(np.asarray(results), axis=0)
            for name, val in zip(self.eval_fetch_list, means):
                context.eval_results.setdefault(name, []).append(float(val))
            _logger.info("epoch %d eval: %s", context.epoch_id,
                         dict(zip(self.eval_fetch_list, means)))


def _names(fetch_list):
    out = []
    for f in (fetch_list or []):
        out.append(f if isinstance(f, str) else f.name)
    return out


class ConfigFactory:
    """yaml strategy factory (ref config.py:29).

    Format (same shape as the reference):

        version: 1.0
        pruners:            # any section defining an object
          pruner_1:
            class: Pruner
        strategies:
          prune_s:
            class: UniformPruneStrategy
            pruner: pruner_1        # reference to another section
            start_epoch: 0
            target_ratio: 0.5
        compressor:
          epoch: 10
          strategies: [prune_s]

    Classes resolve from the slim registry (paddle_tpu.slim namespace) —
    register extras via ConfigFactory.register(cls).
    """

    _REGISTRY = {}

    def __init__(self, config_path_or_dict):
        import yaml
        if isinstance(config_path_or_dict, dict):
            cfg = config_path_or_dict
        else:
            with open(config_path_or_dict) as f:
                cfg = yaml.safe_load(f)
        self._sections = {}
        for key, val in cfg.items():
            if key in ("version", "compressor"):
                continue
            if isinstance(val, dict):
                for name, spec in val.items():
                    if isinstance(spec, dict) and "class" in spec:
                        self._sections[name] = spec
        self.compressor = cfg.get("compressor", {})
        self._cache = {}

    @classmethod
    def register(cls, klass):
        cls._REGISTRY[klass.__name__] = klass
        return klass

    def _resolve_class(self, name):
        if name in self._REGISTRY:
            return self._REGISTRY[name]
        slim = importlib.import_module("paddle_tpu.slim")
        if hasattr(slim, name):
            return getattr(slim, name)
        raise ValueError(f"unknown slim class {name!r}; register it via "
                         "ConfigFactory.register")

    def instance(self, name):
        if name in self._cache:
            return self._cache[name]
        spec = dict(self._sections[name])
        klass = self._resolve_class(spec.pop("class"))
        kwargs = {}
        for k, v in spec.items():
            # a string naming another section instantiates recursively
            kwargs[k] = (self.instance(v)
                         if isinstance(v, str) and v in self._sections
                         else v)
        obj = klass(**kwargs)
        self._cache[name] = obj
        return obj
