"""Distillation loss helpers (static-graph layers).

Parity: the reference's slim distillation strategies — soft-label
(Hinton KD), FSP matrix, and L2 hint losses combined with the student's
task loss.
"""

from .. import layers


def soft_label_loss(student_logits, teacher_logits, temperature=4.0):
    """KL(teacher_T || student_T) * T^2 (Hinton distillation)."""
    t = float(temperature)
    s = layers.softmax(layers.scale(student_logits, scale=1.0 / t))
    p = layers.softmax(layers.scale(teacher_logits, scale=1.0 / t))
    # KL(p||s) = sum p * (log p - log s)
    log_s = layers.log(layers.clip(s, min=1e-8, max=1.0))
    log_p = layers.log(layers.clip(p, min=1e-8, max=1.0))
    kl = layers.reduce_sum(
        layers.elementwise_mul(p, layers.elementwise_sub(log_p, log_s)),
        dim=-1)
    return layers.scale(layers.mean(kl), scale=t * t)


def l2_hint_loss(student_feat, teacher_feat):
    """FitNets hint: L2 between intermediate feature maps."""
    return layers.mean(layers.square_error_cost(student_feat, teacher_feat))


def fsp_loss(student_a, student_b, teacher_a, teacher_b):
    """FSP (flow of solution procedure) matrix distance between two layer
    pairs. Inputs are (N, C, H, W) feature maps; the FSP matrix is the
    HW-averaged Gram matrix between the pair's channels."""
    def fsp_matrix(a, b):
        n = a.shape[0] if a.shape[0] and a.shape[0] > 0 else -1
        ca, cb = a.shape[1], b.shape[1]
        hw = a.shape[2] * a.shape[3]
        af = layers.reshape(a, shape=[n, ca, hw])
        bf = layers.reshape(b, shape=[n, cb, hw])
        g = layers.matmul(af, layers.transpose(bf, perm=[0, 2, 1]))
        return layers.scale(g, scale=1.0 / float(hw))

    gs = fsp_matrix(student_a, student_b)
    gt = fsp_matrix(teacher_a, teacher_b)
    return layers.mean(layers.square_error_cost(gs, gt))


def merge_programs(student_program, teacher_program, prefix="teacher_",
                   share=(), scope=None):
    """Merge a (forward-only) teacher program into the student program.

    Parity: contrib/slim/graph/graph_wrapper.py GraphWrapper.merge — the
    reference splices teacher IR nodes in with renamed vars. Here the
    teacher program is deep-copied, every var/op renamed with `prefix`
    EXCEPT names in `share` (the data inputs both nets read), teacher
    params marked non-trainable, and the result appended into the
    student's global block. When `scope` is given, scope entries for
    renamed persistable vars migrate to the prefixed names (the teacher
    was started/loaded under its original names). Returns the student
    program.
    """
    import copy as _copy
    t = _copy.deepcopy(teacher_program)
    sb = student_program.global_block()
    tb = t.global_block()
    rename = {n: prefix + n for n in tb.vars if n not in share}
    for name, var in tb.vars.items():
        if name in share:
            continue
        var.name = rename[name]
        if hasattr(var, "trainable"):
            var.trainable = False
        if var.name in sb.vars:
            # silently aliasing two same-named teachers corrupts both;
            # the caller must pick distinct prefixes
            raise ValueError(
                f"merge_programs: var {var.name!r} already exists in the "
                f"target program — merge each teacher with a distinct "
                f"prefix")
        sb.vars[var.name] = var
        var.block = sb
    for op in tb.ops:
        op.inputs = {k: [rename.get(n, n) for n in v]
                     for k, v in op.inputs.items()}
        op.outputs = {k: [rename.get(n, n) for n in v]
                      for k, v in op.outputs.items()}
        op.block = sb
        sb.ops.append(op)
    if scope is not None:
        for old, new in rename.items():
            val = scope.get(old)
            if val is not None and scope.get(new) is None:
                scope.set(new, val)
    student_program._bump_version()
    return student_program


class L2Distiller:
    """Parity: contrib/slim/distillation/distiller.py L2Distiller —
    L2 between a student feature and (merged-prefix) teacher feature."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph):
        s = graph.var(self.student_feature_map)._var
        t = graph.var(self.teacher_feature_map)._var
        from .. import layers as L
        from ..core.framework import program_guard
        with program_guard(graph.program):
            loss = L.scale(l2_hint_loss(s, t), scale=float(self.weight))
        return loss


class SoftLabelDistiller:
    """Parity: distiller.py SoftLabelDistiller — tempered KD loss."""

    def __init__(self, student_feature_map=None, teacher_feature_map=None,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph):
        from .. import layers as L
        from ..core.framework import program_guard
        s = graph.var(self.student_feature_map)._var
        t = graph.var(self.teacher_feature_map)._var
        with program_guard(graph.program):
            # reference applies separate temperatures to each side before
            # the KD term (distiller.py SoftLabelDistillerPass)
            s = L.scale(s, scale=1.0 / float(self.student_temperature))
            t = L.scale(t, scale=1.0 / float(self.teacher_temperature))
            loss = L.scale(soft_label_loss(s, t, temperature=1.0),
                           scale=float(self.weight))
        return loss


class FSPDistiller:
    """Parity: distiller.py FSPDistiller — FSP matrix distance over
    (student_pairs[i], teacher_pairs[i]) feature-map name pairs."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph):
        from .. import layers as L
        from ..core.framework import program_guard
        with program_guard(graph.program):
            losses = []
            for (sa, sb), (ta, tb) in zip(self.student_pairs,
                                          self.teacher_pairs):
                losses.append(fsp_loss(graph.var(sa)._var,
                                       graph.var(sb)._var,
                                       graph.var(ta)._var,
                                       graph.var(tb)._var))
            total = losses[0]
            for x in losses[1:]:
                total = L.elementwise_add(total, x)
            total = L.scale(total, scale=float(self.weight))
        return total


from .core import Strategy  # noqa: E402  (after the loss helpers above)


class DistillationStrategy(Strategy):
    """Parity: distillation/distillation_strategy.py — at start_epoch,
    merge the teacher graph(s) into the train graph (first teacher under
    'teacher_', the i-th under 'teacher{i}_'), sum the distiller losses
    onto the task loss, and re-minimize with the distiller optimizer;
    the compressor then trains the combined program."""

    def __init__(self, distillers=None, start_epoch=0, end_epoch=0,
                 task_loss=None, share_vars=()):
        super().__init__(start_epoch, end_epoch)
        self.distillers = list(distillers or [])
        self.task_loss = task_loss
        self.share_vars = tuple(share_vars)

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        from .. import layers as L
        from ..core.executor import Executor, scope_guard
        from ..core.framework import Program, program_guard
        graph = context.train_graph
        for i, tg in enumerate(context.teacher_graphs):
            prefix = "teacher_" if i == 0 else f"teacher{i}_"
            merge_programs(graph.program, tg.program, prefix=prefix,
                           share=self.share_vars, scope=context.scope)
        losses = [d.distiller_loss(graph) for d in self.distillers]
        # minimize() creates optimizer state (lr var, accumulators) whose
        # initializers land in a FRESH startup program — running the
        # original startup again would re-randomize the nets
        opt_startup = Program()
        with program_guard(graph.program, opt_startup):
            total = losses[0]
            for x in losses[1:]:
                total = L.elementwise_add(total, x)
            if self.task_loss is not None:
                total = L.elementwise_add(
                    total, graph.var(self.task_loss)._var)
            if context.distiller_optimizer is not None:
                context.distiller_optimizer.minimize(total)
        if opt_startup.global_block().ops:
            with scope_guard(context.scope):
                Executor(context.place).run(opt_startup)
        graph.out_nodes["distill_loss"] = total.name
