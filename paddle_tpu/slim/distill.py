"""Distillation loss helpers (static-graph layers).

Parity: the reference's slim distillation strategies — soft-label
(Hinton KD), FSP matrix, and L2 hint losses combined with the student's
task loss.
"""

from .. import layers


def soft_label_loss(student_logits, teacher_logits, temperature=4.0):
    """KL(teacher_T || student_T) * T^2 (Hinton distillation)."""
    t = float(temperature)
    s = layers.softmax(layers.scale(student_logits, scale=1.0 / t))
    p = layers.softmax(layers.scale(teacher_logits, scale=1.0 / t))
    # KL(p||s) = sum p * (log p - log s)
    log_s = layers.log(layers.clip(s, min=1e-8, max=1.0))
    log_p = layers.log(layers.clip(p, min=1e-8, max=1.0))
    kl = layers.reduce_sum(
        layers.elementwise_mul(p, layers.elementwise_sub(log_p, log_s)),
        dim=-1)
    return layers.scale(layers.mean(kl), scale=t * t)


def l2_hint_loss(student_feat, teacher_feat):
    """FitNets hint: L2 between intermediate feature maps."""
    return layers.mean(layers.square_error_cost(student_feat, teacher_feat))


def fsp_loss(student_a, student_b, teacher_a, teacher_b):
    """FSP (flow of solution procedure) matrix distance between two layer
    pairs. Inputs are (N, C, H, W) feature maps; the FSP matrix is the
    HW-averaged Gram matrix between the pair's channels."""
    def fsp_matrix(a, b):
        n = a.shape[0] if a.shape[0] and a.shape[0] > 0 else -1
        ca, cb = a.shape[1], b.shape[1]
        hw = a.shape[2] * a.shape[3]
        af = layers.reshape(a, shape=[n, ca, hw])
        bf = layers.reshape(b, shape=[n, cb, hw])
        g = layers.matmul(af, layers.transpose(bf, perm=[0, 2, 1]))
        return layers.scale(g, scale=1.0 / float(hw))

    gs = fsp_matrix(student_a, student_b)
    gt = fsp_matrix(teacher_a, teacher_b)
    return layers.mean(layers.square_error_cost(gs, gt))
