"""Magnitude pruning with persistent masks.

Parity: the reference's slim PruneStrategy/Pruner (ratio-based magnitude
pruning of conv/fc weights, masks re-applied so pruned weights stay zero
through training).

TPU-native: the mask is a non-trainable persistable param and the mask
multiply is a graph op inserted after the backward/optimizer section — the
whole (step + re-mask) remains ONE fused XLA executable, so masking is free
on the HBM path (fused elementwise).
"""

import numpy as np

import jax.numpy as jnp

from ..core.framework import Operator, Parameter
from .. import initializer as init_mod


def magnitude_mask(value, ratio):
    """Zero out the `ratio` smallest-|w| entries. Returns a 0/1 mask."""
    v = np.asarray(value)
    k = int(ratio * v.size)
    if k <= 0:
        return np.ones_like(v)
    thresh = np.partition(np.abs(v).ravel(), k - 1)[k - 1]
    return (np.abs(v) > thresh).astype(v.dtype)


def sensitivity_prune_ratios(param_names, base_ratio=0.5, decay=1.0):
    """Uniform (or geometrically decaying) ratio schedule per param —
    the reference's uniform PruneStrategy default."""
    return {n: base_ratio * (decay ** i) for i, n in enumerate(param_names)}


class Pruner:
    """prune(program, scope, ratios): compute masks from current weights,
    install them as persistable mask params, and append mask-multiply ops
    so every optimizer step re-zeros pruned weights."""

    def __init__(self, criterion="magnitude"):
        assert criterion == "magnitude"
        self.masks = {}

    def prune(self, program, scope, ratios, startup_program=None):
        block = program.global_block()
        startup_block = (startup_program.global_block()
                         if startup_program is not None else None)
        for name, ratio in ratios.items():
            var = block._find_var_recursive(name)
            if var is None or not isinstance(var, Parameter):
                raise ValueError(f"no parameter named {name}")
            value = scope.get(name)
            if value is None:
                raise RuntimeError(
                    f"{name} not materialized; run startup first")
            mask = magnitude_mask(value, ratio)
            mask_name = f"{name}.prune_mask"
            mparam = block.create_parameter(
                name=mask_name, shape=list(mask.shape), dtype=str(mask.dtype),
                trainable=False)
            init_mod.NumpyArrayInitializer(mask)(mparam, startup_block)
            # masks live in the scope immediately too (no re-startup needed)
            scope.set(mask_name, jnp.asarray(mask))
            scope.set(name, jnp.asarray(np.asarray(value) * mask))
            # re-mask after the update ops so pruned weights stay zero
            block.append_op("elementwise_mul",
                            {"X": [name], "Y": [mask_name]},
                            {"Out": [name]}, {"axis": -1})
            self.masks[name] = mask
        return program

    def sparsity(self, scope, names=None):
        names = names or list(self.masks)
        out = {}
        for n in names:
            v = np.asarray(scope.get(n))
            out[n] = float((v == 0).mean())
        return out
