"""Magnitude pruning with persistent masks.

Parity: the reference's slim PruneStrategy/Pruner (ratio-based magnitude
pruning of conv/fc weights, masks re-applied so pruned weights stay zero
through training).

TPU-native: the mask is a non-trainable persistable param and the mask
multiply is a graph op inserted after the backward/optimizer section — the
whole (step + re-mask) remains ONE fused XLA executable, so masking is free
on the HBM path (fused elementwise).
"""

import numpy as np

import jax.numpy as jnp

from ..core.framework import Operator, Parameter
from .. import initializer as init_mod


def magnitude_mask(value, ratio):
    """Zero out the `ratio` smallest-|w| entries. Returns a 0/1 mask."""
    v = np.asarray(value)
    k = int(ratio * v.size)
    if k <= 0:
        return np.ones_like(v)
    thresh = np.partition(np.abs(v).ravel(), k - 1)[k - 1]
    return (np.abs(v) > thresh).astype(v.dtype)


def sensitivity_prune_ratios(param_names, base_ratio=0.5, decay=1.0):
    """Uniform (or geometrically decaying) ratio schedule per param —
    the reference's uniform PruneStrategy default."""
    return {n: base_ratio * (decay ** i) for i, n in enumerate(param_names)}


class Pruner:
    """prune(program, scope, ratios): compute masks from current weights,
    install them as persistable mask params, and append mask-multiply ops
    so every optimizer step re-zeros pruned weights."""

    def __init__(self, criterion="magnitude"):
        assert criterion == "magnitude"
        self.masks = {}

    def prune(self, program, scope, ratios, startup_program=None):
        block = program.global_block()
        startup_block = (startup_program.global_block()
                         if startup_program is not None else None)
        for name, ratio in ratios.items():
            var = block._find_var_recursive(name)
            if var is None or not isinstance(var, Parameter):
                raise ValueError(f"no parameter named {name}")
            value = scope.get(name)
            if value is None:
                raise RuntimeError(
                    f"{name} not materialized; run startup first")
            mask = magnitude_mask(value, ratio)
            mask_name = f"{name}.prune_mask"
            mparam = block.create_parameter(
                name=mask_name, shape=list(mask.shape), dtype=str(mask.dtype),
                trainable=False)
            init_mod.NumpyArrayInitializer(mask)(mparam, startup_block)
            # masks live in the scope immediately too (no re-startup needed)
            scope.set(mask_name, jnp.asarray(mask))
            scope.set(name, jnp.asarray(np.asarray(value) * mask))
            # re-mask after the update ops so pruned weights stay zero
            block.append_op("elementwise_mul",
                            {"X": [name], "Y": [mask_name]},
                            {"Out": [name]}, {"axis": -1})
            self.masks[name] = mask
        return program

    def sparsity(self, scope, names=None):
        names = names or list(self.masks)
        out = {}
        for n in names:
            v = np.asarray(scope.get(n))
            out[n] = float((v == 0).mean())
        return out


class StructurePruner:
    """Whole-filter (structured) pruning decisions.

    Parity: contrib/slim/prune/pruner.py:StructurePruner — picks which
    output channels of a conv/fc weight to remove by group L1 norm.
    Here pruned channels are MASKED (zeroed via the persistent-mask
    mechanism) rather than physically resized: XLA's fused mask multiply
    is free, while shape-changing surgery would force a recompile per
    ratio and break alignment-friendly static shapes on the MXU.
    """

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indexes of the `ratio` lowest-L1 slices along `axis`."""
        v = np.asarray(param)
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*", 0))
        prune_num = int(round(ratio * v.shape[axis]))
        reduce_axes = tuple(i for i in range(v.ndim) if i != axis)
        scores = np.abs(v).sum(axis=reduce_axes)
        return np.argsort(scores)[:prune_num]

    def structure_mask(self, param, pruned_idx, axis=0):
        v = np.asarray(param)
        mask = np.ones_like(v)
        index = [slice(None)] * v.ndim
        index[axis] = np.asarray(pruned_idx, dtype=np.int64)
        mask[tuple(index)] = 0
        return mask


from .core import Strategy  # noqa: E402  (after the mask helpers above)


class PruneStrategy(Strategy):
    """Base pruning strategy (ref prune_strategy.py:PruneStrategy):
    at start_epoch, install magnitude masks for params matching
    `pruned_params` at `target_ratio`."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params="conv.*weights"):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or Pruner()
        self.target_ratio = target_ratio
        self.metric_name = metric_name
        self.pruned_params = pruned_params

    def _matched_params(self, context):
        import re
        pat = re.compile(self.pruned_params)
        return [p.name() for p in context.train_graph.all_parameters()
                if pat.match(p.name()) and not p.name().endswith(
                    ".prune_mask")]

    def _ratios(self, context):
        return {n: self.target_ratio for n in self._matched_params(context)}

    def _eval_metric(self, context):
        """Mean of the chosen eval fetch over context.eval_reader (same
        feed contract as Compressor._eval: dict batches pass through,
        sequences zip with context.eval_feed_list)."""
        from ..core.executor import Executor, scope_guard
        if context.eval_graph is None or context.eval_reader is None:
            return None
        exe = Executor(context.place)
        fetch = self.metric_name or next(
            iter(context.eval_graph.out_nodes.values()), None)
        if fetch is None:
            return None
        vals = []
        for data in context.eval_reader():
            feed = (data if isinstance(data, dict)
                    else dict(zip(context.eval_feed_list, data)))
            with scope_guard(context.scope):
                out = exe.run(context.eval_graph.program, feed=feed,
                              fetch_list=[fetch])
            vals.append(float(np.asarray(out[0]).reshape(-1)[0]))
        return float(np.mean(vals)) if vals else None

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        ratios = self._ratios(context)
        if ratios:
            self.pruner.prune(context.train_graph.program, context.scope,
                              ratios)


class UniformPruneStrategy(PruneStrategy):
    """Same ratio everywhere (ref prune_strategy.py:UniformPruneStrategy)."""


class SensitivePruneStrategy(PruneStrategy):
    """Sensitivity-weighted ratios (ref SensitivePruneStrategy):
    params whose masking hurts the eval metric least get pruned more.
    Sensitivity of p = metric drop when p alone is pruned at
    `probe_ratio`; ratios scale inversely with normalized sensitivity
    around target_ratio (capped to [0, 0.9])."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params="conv.*weights", probe_ratio=0.3):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self.probe_ratio = probe_ratio

    def _ratios(self, context):
        names = self._matched_params(context)
        base = self._eval_metric(context)
        if base is None:
            return {n: self.target_ratio for n in names}
        sens = {}
        for n in names:
            saved = np.asarray(context.scope.get(n)).copy()
            masked = saved * magnitude_mask(saved, self.probe_ratio)
            context.scope.set(n, masked)
            metric = self._eval_metric(context)
            context.scope.set(n, saved)
            sens[n] = max(base - (metric if metric is not None else base),
                          0.0)
        mean_s = np.mean(list(sens.values())) or 1.0
        return {n: float(np.clip(
            self.target_ratio * (2 - sens[n] / mean_s), 0.0, 0.9))
            for n in names}


class AutoPruneStrategy(PruneStrategy):
    """SA search over per-param ratios (ref auto_prune_strategy.py):
    candidates are ratio vectors; reward = eval metric after masking at
    those ratios; best vector wins after `max_try_number` rounds."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params="conv.*weights", max_try_number=10,
                 seed=0):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self.max_try_number = max_try_number
        self.seed = seed

    def _ratios(self, context):
        from .nas import SAController
        names = self._matched_params(context)
        if not names:
            return {}
        base = self._eval_metric(context)
        if base is None:
            return {n: self.target_ratio for n in names}
        # ratio levels per param: target +/- 40% in 5 steps
        levels = np.clip(np.linspace(0.6, 1.4, 5) * self.target_ratio,
                         0.0, 0.9)
        controller = SAController(seed=self.seed,
                                  max_try_number=self.max_try_number)
        mid = len(levels) // 2
        controller.reset([len(levels)] * len(names), [mid] * len(names))
        tokens = [mid] * len(names)
        best, best_reward = None, -np.inf
        for _ in range(self.max_try_number):
            ratios = {n: float(levels[t]) for n, t in zip(names, tokens)}
            saved = {n: np.asarray(context.scope.get(n)).copy()
                     for n in names}
            for n, r in ratios.items():
                context.scope.set(n, saved[n] * magnitude_mask(saved[n], r))
            metric = self._eval_metric(context)
            for n in names:
                context.scope.set(n, saved[n])
            reward = metric if metric is not None else -np.inf
            if reward > best_reward:
                best, best_reward = ratios, reward
            controller.update(tokens, reward)
            tokens = controller.next_tokens()
        return best or {n: self.target_ratio for n in names}
