from .state import (save_vars, save_params, save_persistables, load_vars,
                    load_params, load_persistables, is_parameter,
                    is_persistable, get_parameter_value,
                    get_parameter_value_by_name)
from .inference_io import save_inference_model, load_inference_model
from .checkpoint import (save_checkpoint, load_checkpoint,
                         save_checkpoint_async, save_checkpoint_sharded,
                         load_checkpoint_sharded, CheckpointHandle)
from .fluid_format import (load_fluid_vars, save_fluid_vars,
                           load_fluid_persistables)
from .fluid_proto import (parse_program_desc, encode_program_desc,
                          load_fluid_inference_model,
                          save_fluid_inference_model)
