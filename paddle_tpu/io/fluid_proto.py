"""Reference `__model__` interop: decode Fluid ProgramDesc protobufs.

Parity: paddle/fluid/framework/framework.proto (ProgramDesc/BlockDesc/
OpDesc/VarDesc wire schema) and fluid.io.load_inference_model — the
`__model__` file `save_inference_model` exported. A user switching from
the reference serves their exported graphs directly:

    prog, feeds, fetches = io.load_fluid_inference_model(dirname, exe)
    out = exe.run(prog, feed={feeds[0]: x}, fetch_list=fetches)

The tiny proto2 wire reader is hand-rolled (like io/fluid_format.py): the
format is fixed by the reference's wire compatibility. Ops decode onto
our registry under their fluid names, so anything the surface audit
covers executes; unknown op types raise with a clear message.
"""

import os
import struct

import numpy as np

from .fluid_format import (_DTYPE_BY_ENUM, _read_varint, load_fluid_vars,
                           parse_tensor_desc_wire)


def _fields(buf):
    """Yield (field_number, wire_type, value) from a proto2 blob.
    wire 0 -> int, 2 -> bytes, 5 -> 4 raw bytes, 1 -> 8 raw bytes."""
    off = 0
    while off < len(buf):
        tag, off = _read_varint(buf, off)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wire == 5:
            val = buf[off:off + 4]
            off += 4
        elif wire == 1:
            val = buf[off:off + 8]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _signed(v, bits=64):
    return v - (1 << bits) if v >= (1 << (bits - 1)) else v


def _i32(v):
    # proto int32 negatives arrive as 64-bit two's-complement varints
    return _signed(v & 0xFFFFFFFFFFFFFFFF, 64) if v >= (1 << 31) else v


def _parse_attr(buf):
    """OpDesc.Attr -> (name, python value)."""
    name, atype = None, None
    scalars = {}
    ints, floats, strings, bools, longs, blocks_idx = [], [], [], [], [], []
    for field, wire, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            atype = val
        elif field == 3:
            scalars["i"] = _i32(val)
        elif field == 4:
            scalars["f"] = struct.unpack("<f", val)[0]
        elif field == 5:
            scalars["s"] = val.decode()
        elif field == 6:
            if wire == 2:      # packed
                off = 0
                while off < len(val):
                    v, off = _read_varint(val, off)
                    ints.append(_i32(v))
            else:
                ints.append(_i32(val))
        elif field == 7:
            if wire == 2:
                floats.extend(np.frombuffer(val, "<f4").tolist())
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 8:
            strings.append(val.decode())
        elif field == 10:
            scalars["b"] = bool(val)
        elif field == 11:
            if wire == 2:
                bools.extend(bool(b) for b in val)
            else:
                bools.append(bool(val))
        elif field == 12:
            scalars["block"] = _i32(val)
        elif field == 13:
            scalars["l"] = _signed(val)
        elif field == 14:
            blocks_idx.append(_i32(val))
        elif field == 15:
            if wire == 2:
                off = 0
                while off < len(val):
                    v, off = _read_varint(val, off)
                    longs.append(_signed(v))
            else:
                longs.append(_signed(val))
    # AttrType: INT FLOAT STRING INTS FLOATS STRINGS BOOLEAN BOOLEANS
    #           BLOCK LONG BLOCKS LONGS
    by_type = {0: scalars.get("i"), 1: scalars.get("f"),
               2: scalars.get("s"), 3: ints, 4: floats, 5: strings,
               6: scalars.get("b"), 7: bools, 8: scalars.get("block"),
               9: scalars.get("l"), 10: blocks_idx, 11: longs}
    return name, by_type.get(atype)


def _parse_op_var(buf):
    """OpDesc.Var -> (slot_name, [arg names])."""
    slot, args = None, []
    for field, _wire, val in _fields(buf):
        if field == 1:
            slot = val.decode()
        elif field == 2:
            args.append(val.decode())
    return slot, args


def _parse_op(buf):
    inputs, outputs, attrs = {}, {}, {}
    op_type = None
    for field, _wire, val in _fields(buf):
        if field == 1:
            k, v = _parse_op_var(val)
            inputs[k] = v
        elif field == 2:
            k, v = _parse_op_var(val)
            outputs[k] = v
        elif field == 3:
            op_type = val.decode()
        elif field == 4:
            k, v = _parse_attr(val)
            attrs[k] = v
    return op_type, inputs, outputs, attrs


def _parse_var_type(buf):
    """VarType -> (kind enum, dtype str or None, dims or None)."""
    kind, dtype, dims = None, None, None
    for field, _wire, val in _fields(buf):
        if field == 1:
            kind = val
        elif field in (3, 4):            # LoDTensorDesc / TensorArrayDesc
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:              # inner TensorDesc
                    enum, dims = parse_tensor_desc_wire(bytes(v2))
                    if enum in _DTYPE_BY_ENUM:
                        dtype = np.dtype(_DTYPE_BY_ENUM[enum]).name
        elif field == 2:                 # selected_rows TensorDesc
            enum, dims = parse_tensor_desc_wire(bytes(val))
            if enum in _DTYPE_BY_ENUM:
                dtype = np.dtype(_DTYPE_BY_ENUM[enum]).name
    return kind, dtype, dims


def _parse_var(buf):
    name, persistable = None, False
    kind, dtype, dims = None, None, None
    for field, _wire, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            kind, dtype, dims = _parse_var_type(val)
        elif field == 3:
            persistable = bool(val)
    return name, kind, dtype, dims, persistable


def parse_program_desc(raw):
    """ProgramDesc bytes -> paddle_tpu framework.Program."""
    from ..core.framework import Program, Block, Variable, Operator

    program = Program()
    program.blocks = []
    for field, _wire, val in _fields(raw):
        if field != 1:                   # blocks
            continue
        idx, parent_idx, vars_raw, ops_raw = 0, -1, [], []
        for f2, _w2, v2 in _fields(val):
            if f2 == 1:
                idx = _i32(v2)
            elif f2 == 2:
                parent_idx = _i32(v2)
            elif f2 == 3:
                vars_raw.append(v2)
            elif f2 == 4:
                ops_raw.append(v2)
        blk = Block(program, idx, parent_idx)
        for vb in vars_raw:
            name, kind, dtype, dims, persistable = _parse_var(vb)
            if name in ("feed", "fetch") or kind in (9, 10):
                continue                 # feed/fetch plumbing: not needed
            var = Variable(blk, name=name, shape=dims or [],
                           dtype=dtype or "float32",
                           persistable=persistable)
            blk.vars[name] = var
        for ob in ops_raw:
            op_type, inputs, outputs, attrs = _parse_op(ob)
            op = Operator(blk, op_type, None, None, attrs)
            op.inputs = inputs
            op.outputs = outputs
            blk.ops.append(op)
        program.blocks.append(blk)
    if not program.blocks:
        program.blocks = [Block(program, 0)]
    program._is_test = True
    program._bump_version()
    return program


def _strip_feed_fetch(program):
    """Remove feed/fetch ops; return (feed_names, fetch_names) in col
    order — our Executor feeds/fetches by name directly."""
    gb = program.global_block()
    feeds, fetches = [], []
    kept = []
    for op in gb.ops:
        if op.type == "feed":
            col = op.attr("col", len(feeds))
            name = op.output("Out")[0]
            feeds.append((col, name))
            if name in gb.vars:
                # feed targets validate like layers.data vars: Executor.run
                # raises a clear missing-feed error instead of a trace error
                gb.vars[name].is_data = True
        elif op.type == "fetch":
            col = op.attr("col", len(fetches))
            fetches.append((col, op.input("X")[0]))
        else:
            kept.append(op)
    gb.ops = kept
    program._bump_version()
    return ([n for _, n in sorted(feeds)], [n for _, n in sorted(fetches)])


def load_fluid_inference_model(dirname, executor=None, model_filename=None,
                               params_filename=None, scope=None):
    """Load a model exported by the REFERENCE's save_inference_model.

    Reads `__model__` (or model_filename), builds the Program on our op
    registry, loads the per-var (or combined params_filename) weights
    into the scope, and returns (program, feed_names, fetch_names) —
    the same contract as our load_inference_model.
    """
    from ..core.executor import global_scope
    import jax.numpy as jnp

    path = os.path.join(dirname, model_filename or "__model__")
    with open(path, "rb") as f:
        program = parse_program_desc(f.read())
    feed_names, fetch_names = _strip_feed_fetch(program)

    gb = program.global_block()
    persist = [v.name for v in gb.vars.values() if v.persistable]
    loaded = load_fluid_vars(
        dirname,
        var_names=persist if params_filename else None,
        filename=params_filename)
    scope = scope or global_scope()
    for name in persist:
        if name in loaded:
            scope.set(name, jnp.asarray(loaded[name]))
    missing = [n for n in persist if n not in loaded]
    if missing:
        raise ValueError(f"inference model params missing: {missing}")
    return program, feed_names, fetch_names
