"""Reference `__model__` interop: decode Fluid ProgramDesc protobufs.

Parity: paddle/fluid/framework/framework.proto (ProgramDesc/BlockDesc/
OpDesc/VarDesc wire schema) and fluid.io.load_inference_model — the
`__model__` file `save_inference_model` exported. A user switching from
the reference serves their exported graphs directly:

    prog, feeds, fetches = io.load_fluid_inference_model(dirname, exe)
    out = exe.run(prog, feed={feeds[0]: x}, fetch_list=fetches)

The tiny proto2 wire reader is hand-rolled (like io/fluid_format.py): the
format is fixed by the reference's wire compatibility. Ops decode onto
our registry under their fluid names, so anything the surface audit
covers executes; unknown op types raise with a clear message.
"""

import os
import struct

import numpy as np

from .fluid_format import (_DTYPE_BY_ENUM, _read_varint, load_fluid_vars,
                           parse_tensor_desc_wire)


def _fields(buf):
    """Yield (field_number, wire_type, value) from a proto2 blob.
    wire 0 -> int, 2 -> bytes, 5 -> 4 raw bytes, 1 -> 8 raw bytes."""
    off = 0
    while off < len(buf):
        tag, off = _read_varint(buf, off)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wire == 5:
            val = buf[off:off + 4]
            off += 4
        elif wire == 1:
            val = buf[off:off + 8]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _signed(v, bits=64):
    return v - (1 << bits) if v >= (1 << (bits - 1)) else v


def _i32(v):
    # proto int32 negatives arrive as 64-bit two's-complement varints
    return _signed(v & 0xFFFFFFFFFFFFFFFF, 64) if v >= (1 << 31) else v


def _parse_attr(buf):
    """OpDesc.Attr -> (name, python value)."""
    name, atype = None, None
    scalars = {}
    ints, floats, strings, bools, longs, blocks_idx = [], [], [], [], [], []
    for field, wire, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            atype = val
        elif field == 3:
            scalars["i"] = _i32(val)
        elif field == 4:
            scalars["f"] = struct.unpack("<f", val)[0]
        elif field == 5:
            scalars["s"] = val.decode()
        elif field == 6:
            if wire == 2:      # packed
                off = 0
                while off < len(val):
                    v, off = _read_varint(val, off)
                    ints.append(_i32(v))
            else:
                ints.append(_i32(val))
        elif field == 7:
            if wire == 2:
                floats.extend(np.frombuffer(val, "<f4").tolist())
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 8:
            strings.append(val.decode())
        elif field == 10:
            scalars["b"] = bool(val)
        elif field == 11:
            if wire == 2:
                bools.extend(bool(b) for b in val)
            else:
                bools.append(bool(val))
        elif field == 12:
            scalars["block"] = _i32(val)
        elif field == 13:
            scalars["l"] = _signed(val)
        elif field == 14:
            blocks_idx.append(_i32(val))
        elif field == 15:
            if wire == 2:
                off = 0
                while off < len(val):
                    v, off = _read_varint(val, off)
                    longs.append(_signed(v))
            else:
                longs.append(_signed(val))
    # AttrType: INT FLOAT STRING INTS FLOATS STRINGS BOOLEAN BOOLEANS
    #           BLOCK LONG BLOCKS LONGS
    by_type = {0: scalars.get("i"), 1: scalars.get("f"),
               2: scalars.get("s"), 3: ints, 4: floats, 5: strings,
               6: scalars.get("b"), 7: bools, 8: scalars.get("block"),
               9: scalars.get("l"), 10: blocks_idx, 11: longs}
    return name, by_type.get(atype)


def _parse_op_var(buf):
    """OpDesc.Var -> (slot_name, [arg names])."""
    slot, args = None, []
    for field, _wire, val in _fields(buf):
        if field == 1:
            slot = val.decode()
        elif field == 2:
            args.append(val.decode())
    return slot, args


def _parse_op(buf):
    inputs, outputs, attrs = {}, {}, {}
    op_type = None
    for field, _wire, val in _fields(buf):
        if field == 1:
            k, v = _parse_op_var(val)
            inputs[k] = v
        elif field == 2:
            k, v = _parse_op_var(val)
            outputs[k] = v
        elif field == 3:
            op_type = val.decode()
        elif field == 4:
            k, v = _parse_attr(val)
            attrs[k] = v
    return op_type, inputs, outputs, attrs


def _parse_var_type(buf):
    """VarType -> (kind enum, dtype str or None, dims or None)."""
    kind, dtype, dims = None, None, None
    for field, _wire, val in _fields(buf):
        if field == 1:
            kind = val
        elif field in (3, 4):            # LoDTensorDesc / TensorArrayDesc
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:              # inner TensorDesc
                    enum, dims = parse_tensor_desc_wire(bytes(v2))
                    if enum in _DTYPE_BY_ENUM:
                        dtype = np.dtype(_DTYPE_BY_ENUM[enum]).name
        elif field == 2:                 # selected_rows TensorDesc
            enum, dims = parse_tensor_desc_wire(bytes(val))
            if enum in _DTYPE_BY_ENUM:
                dtype = np.dtype(_DTYPE_BY_ENUM[enum]).name
    return kind, dtype, dims


def _parse_var(buf):
    name, persistable = None, False
    kind, dtype, dims = None, None, None
    for field, _wire, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            kind, dtype, dims = _parse_var_type(val)
        elif field == 3:
            persistable = bool(val)
    return name, kind, dtype, dims, persistable


def parse_program_desc(raw):
    """ProgramDesc bytes -> paddle_tpu framework.Program."""
    from ..core.framework import Program, Block, Variable, Operator

    program = Program()
    program.blocks = []
    for field, _wire, val in _fields(raw):
        if field != 1:                   # blocks
            continue
        idx, parent_idx, vars_raw, ops_raw = 0, -1, [], []
        for f2, _w2, v2 in _fields(val):
            if f2 == 1:
                idx = _i32(v2)
            elif f2 == 2:
                parent_idx = _i32(v2)
            elif f2 == 3:
                vars_raw.append(v2)
            elif f2 == 4:
                ops_raw.append(v2)
        blk = Block(program, idx, parent_idx)
        for vb in vars_raw:
            name, kind, dtype, dims, persistable = _parse_var(vb)
            if name in ("feed", "fetch") or kind in (9, 10):
                continue                 # feed/fetch plumbing: not needed
            var = Variable(blk, name=name, shape=dims or [],
                           dtype=dtype or "float32",
                           persistable=persistable)
            blk.vars[name] = var
        for ob in ops_raw:
            op_type, inputs, outputs, attrs = _parse_op(ob)
            op = Operator(blk, op_type, None, None, attrs)
            op.inputs = inputs
            op.outputs = outputs
            blk.ops.append(op)
        program.blocks.append(blk)
    if not program.blocks:
        program.blocks = [Block(program, 0)]
    program._is_test = True
    program._bump_version()
    return program


def _strip_feed_fetch(program):
    """Remove feed/fetch ops; return (feed_names, fetch_names) in col
    order — our Executor feeds/fetches by name directly."""
    gb = program.global_block()
    feeds, fetches = [], []
    kept = []
    for op in gb.ops:
        if op.type == "feed":
            col = op.attr("col", len(feeds))
            name = op.output("Out")[0]
            feeds.append((col, name))
            if name in gb.vars:
                # feed targets validate like layers.data vars: Executor.run
                # raises a clear missing-feed error instead of a trace error
                gb.vars[name].is_data = True
        elif op.type == "fetch":
            col = op.attr("col", len(fetches))
            fetches.append((col, op.input("X")[0]))
        else:
            kept.append(op)
    gb.ops = kept
    program._bump_version()
    return ([n for _, n in sorted(feeds)], [n for _, n in sorted(fetches)])


# ---------------------------------------------------------------------------
# Encoding: our Program -> reference ProgramDesc bytes (the reverse path,
# so paddle_tpu-trained models serve on the original PaddlePaddle).
# ---------------------------------------------------------------------------

# the ONE wire writer / dtype map, shared with fluid_format
from .fluid_format import _write_varint as _wvint, _ENUM_BY_DTYPE


def _wtag(out, field, wire):
    _wvint(out, (field << 3) | wire)


def _wbytes(out, field, payload):
    _wtag(out, field, 2)
    _wvint(out, len(payload))
    out.extend(payload)


def _wstr(out, field, s):
    _wbytes(out, field, s.encode())


def _wvarint_field(out, field, v):
    _wtag(out, field, 0)
    _wvint(out, v)


def _encode_attr(name, value):
    """OpDesc.Attr bytes for a python attr value; None for unencodable."""
    out = bytearray()
    _wstr(out, 1, name)
    if name == "sub_block" and isinstance(value, int):
        # control-flow block reference: framework.proto AttrType BLOCK,
        # not INT — While/cond would fail the reference's type check
        _wvarint_field(out, 2, 8)
        _wvarint_field(out, 12, value)
    elif name in ("sub_blocks", "blocks") and isinstance(
            value, (list, tuple)) and all(
            isinstance(v, int) and not isinstance(v, bool) for v in value):
        _wvarint_field(out, 2, 10)                    # BLOCKS
        for v in value:
            _wvarint_field(out, 14, v)
    elif isinstance(value, bool):                     # before int!
        _wvarint_field(out, 2, 6)
        _wvarint_field(out, 10, int(value))
    elif isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            _wvarint_field(out, 2, 0)
            _wvarint_field(out, 3, value)
        else:
            _wvarint_field(out, 2, 9)                 # LONG
            _wvarint_field(out, 13, value)
    elif isinstance(value, float):
        _wvarint_field(out, 2, 1)
        _wtag(out, 4, 5)
        out.extend(struct.pack("<f", value))
    elif isinstance(value, str):
        _wvarint_field(out, 2, 2)
        _wstr(out, 5, value)
    elif isinstance(value, (list, tuple)):
        vals = list(value)
        if not vals:
            # an empty list carries no element-type evidence; emitting a
            # guessed AttrType would fail the reference's C++ type check —
            # omit it (op defaults apply) via the caller's warning path
            return None
        if all(isinstance(v, bool) for v in vals):
            _wvarint_field(out, 2, 7)
            for v in vals:
                _wvarint_field(out, 11, int(v))
        elif all(isinstance(v, int) for v in vals):
            if all(-(1 << 31) <= v < (1 << 31) for v in vals):
                _wvarint_field(out, 2, 3)             # INTS
                for v in vals:
                    _wvarint_field(out, 6, v)
            else:
                _wvarint_field(out, 2, 11)            # LONGS
                for v in vals:
                    _wvarint_field(out, 15, v)
        elif all(isinstance(v, (int, float)) for v in vals):
            # mixed int/float (e.g. aspect_ratios=[1, 2, 0.5]): FLOATS —
            # dropping the attr would silently serve with op defaults
            _wvarint_field(out, 2, 4)
            for v in vals:
                _wtag(out, 7, 5)
                out.extend(struct.pack("<f", float(v)))
        elif all(isinstance(v, str) for v in vals):
            _wvarint_field(out, 2, 5)
            for v in vals:
                _wstr(out, 8, v)
        else:
            return None
    else:
        return None
    return bytes(out)


def _encode_op(op_type, inputs, outputs, attrs):
    out = bytearray()
    for slot, args in inputs.items():
        var = bytearray()
        _wstr(var, 1, slot)
        for a in args:
            _wstr(var, 2, a)
        _wbytes(out, 1, var)
    for slot, args in outputs.items():
        var = bytearray()
        _wstr(var, 1, slot)
        for a in args:
            _wstr(var, 2, a)
        _wbytes(out, 2, var)
    _wstr(out, 3, op_type)
    for name, value in attrs.items():
        enc = _encode_attr(name, value)
        if enc is not None:
            _wbytes(out, 4, enc)
        else:
            import warnings
            if isinstance(value, (list, tuple)) and not value:
                reason = ("is an empty list (no element-type evidence; "
                          "the op default applies on the reference)")
            else:
                reason = f"has unencodable type {type(value).__name__}"
            warnings.warn(
                f"attr '{name}' of op '{op_type}' {reason}; omitted from "
                "the exported ProgramDesc", RuntimeWarning, stacklevel=2)
    return bytes(out)


def _encode_var(name, dtype, dims, persistable, kind=7):
    td = bytearray()
    np_dtype = np.dtype("float32" if dtype in (None, "") else dtype)
    if np_dtype not in _ENUM_BY_DTYPE:
        # e.g. bfloat16: the fluid 1.5 format has no enum for it; writing
        # FP32 silently would make the reference execute wrong numerics
        raise ValueError(
            f"var '{name}' dtype {dtype} has no fluid enum; cast the "
            "program to a supported dtype before exporting")
    _wvarint_field(td, 1, _ENUM_BY_DTYPE[np_dtype])
    for d in dims:
        _wvarint_field(td, 2, int(d))
    lod = bytearray()
    _wbytes(lod, 1, td)
    vtype = bytearray()
    _wvarint_field(vtype, 1, kind)
    if kind == 7:
        _wbytes(vtype, 3, lod)
    out = bytearray()
    _wstr(out, 1, name)
    _wbytes(out, 2, vtype)
    if persistable:
        _wvarint_field(out, 3, 1)
    return bytes(out)


def _slice_for_inference(program, fetch_names):
    """Backward-slice the global block to ops the fetches need, WITHOUT
    Program._prune's keep-persistable-writers rule (that rule preserves
    optimizer/stat updates — training semantics; an inference export must
    shed them, matching the reference's save_inference_model pruning)."""
    gb = program.global_block()
    need = set(fetch_names)
    keep = []
    for op in reversed(gb.ops):
        if set(op.output_names) & need:
            keep.append(op)
            need |= set(op.input_names)
    gb.ops = list(reversed(keep))
    program._bump_version()
    return program


def encode_program_desc(program, feed_names=(), fetch_names=(),
                        only_vars=None):
    """paddle_tpu Program -> reference ProgramDesc bytes, with the feed/
    fetch plumbing the reference's load_inference_model expects.
    `only_vars`: restrict global-block var descs to these names (the
    inference exporter passes the referenced-var set so grad/optimizer
    vars stay out of __model__)."""
    out = bytearray()
    for blk in program.blocks:
        b = bytearray()
        _wvarint_field(b, 1, blk.idx)
        _wvarint_field(b, 2, blk.parent_idx)
        if blk.idx == 0:
            _wbytes(b, 3, _encode_var("feed", "float32", [], True, kind=9))
            _wbytes(b, 3, _encode_var("fetch", "float32", [], True, kind=10))
        for v in blk.vars.values():
            if blk.idx == 0 and only_vars is not None \
                    and v.name not in only_vars:
                continue
            _wbytes(b, 3, _encode_var(
                v.name, getattr(v, "dtype", "float32") or "float32",
                list(getattr(v, "shape", None) or []),
                bool(getattr(v, "persistable", False))))
        if blk.idx == 0:
            for col, name in enumerate(feed_names):
                _wbytes(b, 4, _encode_op(
                    "feed", {"X": ["feed"]}, {"Out": [name]}, {"col": col}))
        for op in blk.ops:
            _wbytes(b, 4, _encode_op(op.type, op.inputs, op.outputs,
                                     op.attrs))
        if blk.idx == 0:
            for col, name in enumerate(fetch_names):
                _wbytes(b, 4, _encode_op(
                    "fetch", {"X": [name]}, {"Out": ["fetch"]},
                    {"col": col}))
        _wbytes(out, 1, b)
    return bytes(out)


def save_fluid_inference_model(dirname, feed_names, fetch_vars, executor,
                               main_program=None, model_filename=None,
                               params_filename=None, scope=None):
    """Export in the REFERENCE's save_inference_model layout (`__model__`
    ProgramDesc + weights), so paddle_tpu-trained models serve on the
    original PaddlePaddle. Contract mirrors our save_inference_model."""
    from ..core import framework
    from ..core.executor import global_scope
    from .fluid_format import save_fluid_vars

    program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    fetch_names = [v.name if hasattr(v, "name") else v for v in fetch_vars]
    pruned = _slice_for_inference(program.clone(for_test=True), fetch_names)
    gb = pruned.global_block()
    referenced = set(feed_names) | set(fetch_names)
    for op in gb.ops:
        referenced |= set(op.input_names) | set(op.output_names)
    # validate EVERYTHING (params present, dtypes encodable) before any
    # write — a partial export (dangling __model__) would flip Predictor's
    # format dispatch for the whole dir
    persist, missing = {}, []
    for v in gb.vars.values():
        if v.persistable and v.name in referenced \
                and v.name not in ("feed", "fetch"):
            val = scope.get(v.name)
            if val is None:
                missing.append(v.name)
            else:
                arr = np.asarray(val)
                if arr.dtype not in _ENUM_BY_DTYPE:
                    raise ValueError(
                        f"param '{v.name}' dtype {arr.dtype} has no fluid "
                        "enum; cast (e.g. bf16 -> fp32) before exporting")
                persist[v.name] = arr
    if missing:
        raise ValueError(
            f"persistables have no value in the scope (did startup run "
            f"here?): {missing}")
    raw = encode_program_desc(pruned, feed_names, fetch_names,
                              only_vars=referenced)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        f.write(raw)
    save_fluid_vars(dirname, persist, filename=params_filename)
    return list(persist)


def load_fluid_inference_model(dirname, executor=None, model_filename=None,
                               params_filename=None, scope=None):
    """Load a model exported by the REFERENCE's save_inference_model.

    Reads `__model__` (or model_filename), builds the Program on our op
    registry, loads the per-var (or combined params_filename) weights
    into the scope, and returns (program, feed_names, fetch_names) —
    the same contract as our load_inference_model.
    """
    from ..core.executor import global_scope
    import jax.numpy as jnp

    path = os.path.join(dirname, model_filename or "__model__")
    with open(path, "rb") as f:
        program = parse_program_desc(f.read())
    feed_names, fetch_names = _strip_feed_fetch(program)

    gb = program.global_block()
    persist = [v.name for v in gb.vars.values() if v.persistable]
    loaded = load_fluid_vars(
        dirname,
        var_names=persist if params_filename else None,
        filename=params_filename)
    scope = scope or global_scope()
    for name in persist:
        if name in loaded:
            scope.set(name, jnp.asarray(loaded[name]))
    missing = [n for n in persist if n not in loaded]
    if missing:
        raise ValueError(f"inference model params missing: {missing}")
    return program, feed_names, fetch_names
