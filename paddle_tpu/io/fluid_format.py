"""Reference-binary checkpoint interop: read/write Fluid's LoDTensor files.

Parity: paddle/fluid/framework/lod_tensor.cc SerializeToStream:219 /
DeserializeFromStream and tensor_util.cc TensorToStream — the on-disk
format `fluid.io.save_params/save_persistables` produced. A user switching
from the reference brings their trained weights as-is:

    n, missing = io.load_fluid_persistables("/path/to/saved_model",
                                            main_program=main)
    # or raw: params = io.load_fluid_vars("/path/to/saved_model")

Layout (little-endian):
  u32 lod_version(0) | u64 lod_levels | per level: u64 nbytes + u64 data[]
  u32 tensor_version(0) | i32 desc_size | TensorDesc proto | raw data
TensorDesc proto (framework.proto VarType.TensorDesc): field 1 varint
data_type enum, field 2 repeated int64 dims (unpacked tags 0x10; packed
0x12 accepted on read). The tiny proto codec is hand-rolled here — the
format is fixed by the reference's wire compatibility, not its code.
"""

import os
import struct

import numpy as np

# framework.proto VarType.Type enum values for POD tensor dtypes
_DTYPE_BY_ENUM = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
                  4: np.float16, 5: np.float32, 6: np.float64,
                  20: np.uint8, 21: np.int8}
_ENUM_BY_DTYPE = {np.dtype(v): k for k, v in _DTYPE_BY_ENUM.items()}


def _read_varint(buf, off):
    result, shift = 0, 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _write_varint(out, value):
    if value < 0:                    # proto2: two's-complement 64-bit
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_tensor_desc_wire(buf):
    """(data_type enum, signed dims) from a VarType.TensorDesc blob —
    the ONE wire parser shared by fluid_format and fluid_proto."""
    off, dtype_enum, dims = 0, None, []
    while off < len(buf):
        tag, off = _read_varint(buf, off)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 0:          # data_type
            dtype_enum, off = _read_varint(buf, off)
        elif field == 2 and wire == 0:        # dims, unpacked
            d, off = _read_varint(buf, off)
            dims.append(_signed64(d))
        elif field == 2 and wire == 2:        # dims, packed
            ln, off = _read_varint(buf, off)
            end = off + ln
            while off < end:
                d, off = _read_varint(buf, off)
                dims.append(_signed64(d))
        elif wire == 0:
            _, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            off += ln
        else:
            raise ValueError(f"unsupported wire type {wire} in TensorDesc")
    return dtype_enum, dims


def _parse_tensor_desc(buf):
    """(np dtype, dims) from a VarType.TensorDesc proto blob."""
    dtype_enum, dims = parse_tensor_desc_wire(buf)
    if dtype_enum not in _DTYPE_BY_ENUM:
        raise ValueError(f"unsupported fluid data_type enum {dtype_enum}")
    return np.dtype(_DTYPE_BY_ENUM[dtype_enum]), dims


def _build_tensor_desc(arr):
    out = bytearray()
    _write_varint(out, (1 << 3) | 0)              # field 1, varint
    _write_varint(out, _ENUM_BY_DTYPE[arr.dtype])
    for d in arr.shape:
        _write_varint(out, (2 << 3) | 0)          # field 2, unpacked
        _write_varint(out, d)
    return bytes(out)


def read_lod_tensor(stream):
    """-> (ndarray, lod) from a reference-format stream (file object)."""
    (lod_version,) = struct.unpack("<I", stream.read(4))
    if lod_version != 0:
        raise ValueError(f"unsupported LoDTensor version {lod_version}")
    (lod_levels,) = struct.unpack("<Q", stream.read(8))
    lod = []
    for _ in range(lod_levels):
        (nbytes,) = struct.unpack("<Q", stream.read(8))
        level = np.frombuffer(stream.read(nbytes), dtype=np.uint64)
        lod.append([int(x) for x in level])
    (tensor_version,) = struct.unpack("<I", stream.read(4))
    if tensor_version != 0:
        raise ValueError(f"unsupported tensor version {tensor_version}")
    (desc_size,) = struct.unpack("<i", stream.read(4))
    dtype, dims = _parse_tensor_desc(stream.read(desc_size))
    count = int(np.prod(dims, dtype=np.int64)) if dims else 1
    data = stream.read(count * dtype.itemsize)
    arr = np.frombuffer(data, dtype=dtype, count=count).reshape(dims)
    return arr.copy(), lod


def write_lod_tensor(stream, arr, lod=None):
    """Write one array in the reference LoDTensor stream format."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _ENUM_BY_DTYPE:
        raise ValueError(f"dtype {arr.dtype} has no fluid enum")
    stream.write(struct.pack("<I", 0))
    lod = lod or []
    stream.write(struct.pack("<Q", len(lod)))
    for level in lod:
        level = np.asarray(level, dtype=np.uint64)
        stream.write(struct.pack("<Q", level.nbytes))
        stream.write(level.tobytes())
    stream.write(struct.pack("<I", 0))
    desc = _build_tensor_desc(arr)
    stream.write(struct.pack("<i", len(desc)))
    stream.write(desc)
    stream.write(arr.tobytes())


def load_fluid_vars(dirname, var_names=None, filename=None):
    """Read reference-saved persistables -> {name: ndarray}.

    Per-var layout (save_persistables with filename=None): one file per
    variable, named by the variable. Combined layout (save_combine):
    `filename` holds the tensors concatenated in `var_names` order —
    var_names is required then, exactly like the reference's load_combine.
    """
    out = {}
    if filename is not None:
        if not var_names:
            raise ValueError("combined-file loading needs var_names order")
        with open(os.path.join(dirname, filename), "rb") as f:
            for name in var_names:
                arr, _lod = read_lod_tensor(f)
                out[name] = arr
            if f.read(1):
                raise ValueError("trailing bytes: var_names incomplete?")
        return out
    explicit = var_names is not None
    names = var_names if explicit else sorted(os.listdir(dirname))
    for name in names:
        path = os.path.join(dirname, name)
        if not os.path.isfile(path):
            if explicit:
                raise FileNotFoundError(
                    f"requested var '{name}' has no file in {dirname}")
            continue
        try:
            with open(path, "rb") as f:
                arr, _lod = read_lod_tensor(f)
        except (ValueError, struct.error, IndexError, EOFError):
            if explicit:
                raise       # explicitly requested: surface the parse error
            continue        # directory scan: skip non-tensor/corrupt files
        out[name] = arr
    return out


def save_fluid_vars(dirname, vars_dict, filename=None, var_order=None):
    """Write {name: array} in the reference binary format (round-trip to
    the original PaddlePaddle — migration works in both directions)."""
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        # default to INSERTION order (callers build vars_dict in program
        # declaration order — what the reference's save_combine writes and
        # what load_fluid_persistables reads back); sorting here would
        # silently permute same-shaped tensors on the round trip
        order = var_order if var_order is not None else list(vars_dict)
        with open(os.path.join(dirname, filename), "wb") as f:
            for name in order:
                write_lod_tensor(f, np.asarray(vars_dict[name]))
        return
    for name, arr in vars_dict.items():
        with open(os.path.join(dirname, name), "wb") as f:
            write_lod_tensor(f, np.asarray(arr))


def load_fluid_persistables(dirname, main_program=None, filename=None,
                            scope=None):
    """Reference-checkpoint -> live scope: reads every persistable var of
    `main_program` (default main) from a reference-format save dir and
    sets it, shape-checked. The migration entry point."""
    import jax.numpy as jnp
    from ..core import framework
    from ..core.executor import global_scope

    program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    names = [v.name for v in program.list_vars()
             if v.persistable and v.name not in ("feed", "fetch")]
    loaded = load_fluid_vars(dirname, var_names=names if filename else None,
                             filename=filename)
    missing, set_count = [], 0
    for v in program.list_vars():
        if not v.persistable or v.name in ("feed", "fetch"):
            continue
        if v.name not in loaded:
            missing.append(v.name)
            continue
        arr = loaded[v.name]
        want = tuple(int(d) for d in v.shape)
        if want:
            ok = len(arr.shape) == len(want) and all(
                w == -1 or int(a) == w for a, w in zip(arr.shape, want))
        else:
            # scalar-declared var: accept () or a single element, reject
            # real tensors (silently storing one breaks far from here)
            ok = arr.ndim == 0 or arr.size == 1
        if not ok:
            raise ValueError(
                f"shape mismatch for '{v.name}': checkpoint "
                f"{tuple(arr.shape)} vs program {want or '()'}")
        scope.set(v.name, jnp.asarray(arr))
        set_count += 1
    return set_count, missing
