"""save/load_inference_model.

Parity: fluid.io.save_inference_model / load_inference_model
(python/paddle/fluid/io.py). The program is serialized as JSON (the
paddle_tpu ProgramDesc format, see core/framework.py) + params as npz.
"""

import json
import os

from ..core.framework import Program, Variable
from .state import save_params, load_params


MODEL_FILENAME = "__model__.json"
PARAMS_FILENAME = "__params__.npz"


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    from ..core.framework import default_main_program
    program = (main_program or default_main_program()).clone(for_test=True)
    program._prune(target_vars)
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": json.loads(program.to_json()),
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name if isinstance(v, Variable) else v
                        for v in target_vars],
    }
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "w") as f:
        json.dump(meta, f)
    save_params(executor, dirname, program,
                filename=params_filename or PARAMS_FILENAME)
    return meta["fetch_names"]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        meta = json.load(f)
    program = Program.from_json(json.dumps(meta["program"]))
    load_params(executor, dirname, program,
                filename=params_filename or PARAMS_FILENAME)
    gb = program.global_block()
    fetch_vars = [gb.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars
