"""Full-training-state checkpoint/resume.

Parity: fluid checkpointing (io.py save/load_persistables + trainer state) —
persistables include optimizer accumulators, LR counters and batch-norm
stats, so save/load_checkpoint round-trips a training run exactly.

For big sharded models, save_checkpoint_sharded writes one file PER DEVICE
SHARD keyed by the array's NamedSharding (orbax-style layout, self-contained
format: index.json + shards/*.npy) — no single file ever holds the full
model, saves can run async behind a completion barrier, and restore is
bitwise and supports partial (per-var) loading onto a new mesh.
"""

import json
import os
import re
import threading

import numpy as np

from ..core.framework import default_main_program
from ..core.executor import global_scope
from .state import save_persistables, load_persistables


def save_checkpoint(executor, dirname, main_program=None, step=0, extra=None):
    os.makedirs(dirname, exist_ok=True)
    save_persistables(executor, dirname, main_program, filename="state.npz")
    meta = {"step": int(step), "extra": extra or {}}
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta, f)


def load_checkpoint(executor, dirname, main_program=None):
    load_persistables(executor, dirname, main_program, filename="state.npz")
    meta_path = os.path.join(dirname, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            return json.load(f)
    return {"step": 0, "extra": {}}


def save_checkpoint_async(executor, dirname, main_program=None, step=0):
    """Async save: snapshot to host in a thread (orbax-style async)."""
    scope = global_scope()
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]
    snapshot = {n: np.asarray(scope.get(n)) for n in names
                if scope.get(n) is not None}

    def _write():
        os.makedirs(dirname, exist_ok=True)
        np.savez(os.path.join(dirname, "state.npz"), **snapshot)
        with open(os.path.join(dirname, "meta.json"), "w") as f:
            json.dump({"step": int(step), "extra": {}}, f)

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# Sharded checkpoint (per-device-shard files keyed by NamedSharding)
# ---------------------------------------------------------------------------

def _safe_name(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _spec_to_json(spec):
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append(list(e))
    return out


def _spec_from_json(entries):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


class CheckpointHandle:
    """Completion barrier for an (async) sharded save."""

    def __init__(self, thread=None, error_box=None):
        self._thread = thread
        self._error_box = error_box or []

    def wait(self):
        if self._thread is not None:
            self._thread.join()
        if self._error_box:
            raise self._error_box[0]
        return True

    result = wait


def save_checkpoint_sharded(executor, dirname, main_program=None, step=0,
                            extra=None, async_save=False, scope=None):
    """Write every persistable as per-shard .npy files.

    A var with a non-trivial NamedSharding contributes one file per unique
    device shard (its global index recorded in index.json); replicated vars
    contribute one file. Device->host transfers happen synchronously (the
    arrays may be donated by the next step); file IO runs in a background
    thread when async_save=True. Returns a CheckpointHandle — call .wait()
    as the completion barrier before relying on the checkpoint.
    """
    from jax.sharding import NamedSharding

    scope = scope or global_scope()
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]

    index = {}
    payloads = []  # (relpath, np.ndarray)
    for n in sorted(set(names)):
        val = scope.get(n)
        if val is None:
            continue
        sharding = getattr(val, "sharding", None)
        entry = {"shape": [int(s) for s in val.shape],
                 "dtype": str(np.dtype(val.dtype)), "shards": []}
        sharded = (isinstance(sharding, NamedSharding)
                   and any(e is not None for e in tuple(sharding.spec))
                   and hasattr(val, "addressable_shards"))
        if sharded:
            entry["spec"] = _spec_to_json(sharding.spec)
            seen = set()
            for sh in val.addressable_shards:
                start = tuple(0 if s.start is None else int(s.start)
                              for s in sh.index)
                if start in seen:
                    continue  # replicated copy of the same shard
                seen.add(start)
                rel = f"shards/{_safe_name(n)}--{len(entry['shards'])}.npy"
                data = np.asarray(sh.data)
                entry["shards"].append({"file": rel, "start": list(start),
                                        "shape": list(data.shape)})
                payloads.append((rel, data))
        else:
            rel = f"shards/{_safe_name(n)}--full.npy"
            data = np.asarray(val)
            entry["shards"].append({"file": rel,
                                    "start": [0] * data.ndim,
                                    "shape": list(data.shape)})
            payloads.append((rel, data))
        index[n] = entry

    meta = {"step": int(step), "extra": extra or {}}
    err_box = []

    def _write():
        try:
            os.makedirs(os.path.join(dirname, "shards"), exist_ok=True)
            for rel, data in payloads:
                np.save(os.path.join(dirname, rel), data)
            # index written LAST: its presence marks a complete checkpoint.
            with open(os.path.join(dirname, "index.json"), "w") as f:
                json.dump({"meta": meta, "vars": index}, f)
        except BaseException as e:  # surfaced at .wait()
            err_box.append(e)

    if async_save:
        t = threading.Thread(target=_write, daemon=True)
        t.start()
        return CheckpointHandle(t, err_box)
    _write()
    return CheckpointHandle(None, err_box)


def load_checkpoint_sharded(executor, dirname, main_program=None, mesh=None,
                            var_names=None, scope=None):
    """Restore from a sharded checkpoint. Assembles each var from its shard
    files (bitwise) and places it back: with `mesh` given, vars that were
    saved sharded are device_put with their recorded PartitionSpec on that
    mesh; otherwise they land replicated/unsharded. var_names restores a
    subset (partial restore). Returns the meta dict ({step, extra})."""
    import jax
    from jax.sharding import NamedSharding

    scope = scope or global_scope()
    index_path = os.path.join(dirname, "index.json")
    if not os.path.exists(index_path):
        raise FileNotFoundError(
            f"{index_path} not found — incomplete or missing checkpoint")
    with open(index_path) as f:
        blob = json.load(f)
    wanted = set(var_names) if var_names is not None else None
    for n, entry in blob["vars"].items():
        if wanted is not None and n not in wanted:
            continue
        full = np.empty(entry["shape"], dtype=np.dtype(entry["dtype"]))
        for sh in entry["shards"]:
            data = np.load(os.path.join(dirname, sh["file"]))
            idx = tuple(slice(st, st + ln)
                        for st, ln in zip(sh["start"], data.shape))
            full[idx] = data
        if mesh is not None and "spec" in entry:
            arr = jax.device_put(
                full, NamedSharding(mesh, _spec_from_json(entry["spec"])))
        else:
            arr = jax.numpy.asarray(full)
        scope.set(n, arr)
    return blob["meta"]
