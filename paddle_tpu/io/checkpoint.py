"""Full-training-state checkpoint/resume.

Parity: fluid checkpointing (io.py save/load_persistables + trainer state) —
persistables include optimizer accumulators, LR counters and batch-norm
stats, so save/load_checkpoint round-trips a training run exactly.

Durability contract (robustness layer, docs/robustness.md): every
checkpoint write is ATOMIC — payload files land via temp file + fsync +
os.replace, and a manifest/commit marker is written LAST (meta.json for
the single-file layout, index.json for the sharded layout), so a crash
at ANY byte leaves either the previous complete checkpoint or an
obviously-incomplete directory, never a loadable-looking torn one. The
manifest carries a per-array CRC32; load validates it and raises
CheckpointCorruptError instead of resuming from silently-corrupt state.

For big sharded models, save_checkpoint_sharded writes one file PER DEVICE
SHARD keyed by the array's NamedSharding (orbax-style layout, self-contained
format: index.json + shards/*.npy) — no single file ever holds the full
model, saves can run async behind a completion barrier, and restore is
bitwise and supports partial (per-var) loading onto a new mesh.
"""

import atexit
import glob
import json
import os
import re
import threading
import time
import warnings
import zlib

import numpy as np

from ..core.framework import default_main_program
from ..core.executor import global_scope
from ..observability import ComponentStats
from .state import _atomic_save, load_persistables

_stats = ComponentStats()

CHECKPOINT_FORMAT = 2


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed manifest/CRC validation (torn write, bit rot,
    or a partial copy). Callers with a retention directory should fall
    back to the previous good checkpoint (CheckpointManager does)."""


# ---------------------------------------------------------------------------
# write-fault hook (robustness/chaos.py): called before every physical
# checkpoint file write so the chaos tier can fail the Nth write
# deterministically on the REAL write path
# ---------------------------------------------------------------------------

_WRITE_FAULT_HOOK = None


def set_write_fault_hook(hook):
    """Install `hook(kind, path)` to run before each physical checkpoint
    write (kinds: 'state', 'meta', 'shard', 'index'). Raising from the
    hook makes that write fail. Pass None to uninstall. Test-only."""
    global _WRITE_FAULT_HOOK
    _WRITE_FAULT_HOOK = hook


def _fault(kind, path):
    if _WRITE_FAULT_HOOK is not None:
        _WRITE_FAULT_HOOK(kind, path)


# ---------------------------------------------------------------------------
# atomic write primitive: the shared temp+fsync+os.replace sequence
# lives in io/state.py (ONE copy); checkpoint writes add the fault hook
# ---------------------------------------------------------------------------

def _atomic_write(path, writer, kind):
    """temp file + fsync + os.replace: `path` either keeps its old
    content or atomically becomes the complete new content."""
    _fault(kind, path)
    _atomic_save(path, writer)


def _crc32(arr):
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _manifest_entry(arr):
    return {"crc32": _crc32(arr), "shape": [int(s) for s in arr.shape],
            "dtype": str(arr.dtype)}


def _snapshot(scope, program):
    """Persistables as host numpy — the device->host sync happens HERE,
    on the caller's thread (the arrays may be donated by the next step),
    never inside a background writer."""
    names = [v.name for v in program.list_vars() if v.persistable]
    return {n: np.asarray(scope.get(n)) for n in sorted(set(names))
            if scope.get(n) is not None}


def _write_state(dirname, snapshot, meta):
    """The single-file layout's committed write sequence: state.npz
    first, then meta.json (manifest + commit marker) LAST."""
    os.makedirs(dirname, exist_ok=True)
    t0 = time.perf_counter()
    _atomic_write(os.path.join(dirname, "state.npz"),
                  lambda f: np.savez(f, **snapshot), "state")
    meta = dict(meta)
    meta["format"] = CHECKPOINT_FORMAT
    meta["manifest"] = {n: _manifest_entry(a) for n, a in snapshot.items()}
    _atomic_write(os.path.join(dirname, "meta.json"),
                  lambda f: f.write(json.dumps(meta).encode()), "meta")
    _stats.count("checkpoint.saves")
    _stats.observe("checkpoint.save_ms", (time.perf_counter() - t0) * 1e3)


def save_checkpoint(executor, dirname, main_program=None, step=0,
                    extra=None, scope=None):
    scope = scope or global_scope()
    program = main_program or default_main_program()
    _write_state(dirname, _snapshot(scope, program),
                 {"step": int(step), "extra": extra or {}})


def load_checkpoint(executor, dirname, main_program=None, scope=None,
                    validate=True):
    """Restore a checkpoint directory. Validates the meta.json manifest
    (per-array CRC32) before touching the scope and raises
    CheckpointCorruptError on mismatch. When `dirname` is a retention
    root holding `ckpt-*` subdirectories (CheckpointManager layout),
    the newest VALID checkpoint is loaded, falling back past corrupt or
    incomplete ones with a warning."""
    scope = scope or global_scope()
    program = main_program or default_main_program()
    meta_path = os.path.join(dirname, "meta.json")
    if not os.path.exists(meta_path):
        subs = sorted(glob.glob(os.path.join(dirname, "ckpt-*")),
                      reverse=True)
        if subs:
            last_err = None
            for sub in subs:
                # a ckpt-* dir without meta.json is an ABORTED save
                # (the marker is written last) — it must not reach the
                # single-dir path below, where a bare state.npz would
                # load as a fake committed step-0 checkpoint
                if not os.path.exists(os.path.join(sub, "meta.json")):
                    _stats.count("checkpoint.fallbacks")
                    warnings.warn(
                        f"checkpoint {sub} has no commit marker "
                        f"(aborted save); falling back to the previous "
                        f"one", stacklevel=2)
                    continue
                try:
                    return load_checkpoint(executor, sub, main_program,
                                           scope=scope, validate=validate)
                except (OSError, ValueError, CheckpointCorruptError) as e:
                    _stats.count("checkpoint.fallbacks")
                    warnings.warn(
                        f"checkpoint {sub} unusable ({e}); falling back "
                        f"to the previous one", stacklevel=2)
                    last_err = e
            raise CheckpointCorruptError(
                f"{dirname}: no loadable checkpoint among "
                f"{len(subs)} candidates") from last_err
    t0 = time.perf_counter()
    state_path = os.path.join(dirname, "state.npz")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    else:
        meta = {"step": 0, "extra": {}}
    if validate and os.path.exists(state_path):
        # ONE read of the archive: validate every array against the
        # manifest, then place the already-decompressed copies straight
        # into the scope (re-reading via load_persistables would double
        # the restore I/O of a large checkpoint)
        import jax.numpy as jnp
        wanted = {v.name for v in program.list_vars() if v.persistable}
        staged = []
        try:
            with np.load(state_path, allow_pickle=False) as data:
                manifest = meta.get("manifest")
                for n in data.files:
                    arr = data[n]
                    if manifest is not None:
                        entry = manifest.get(n)
                        if entry is None or _crc32(arr) != entry["crc32"]:
                            _stats.count("checkpoint.crc_failures")
                            raise CheckpointCorruptError(
                                f"{dirname}: CRC mismatch for '{n}' — "
                                f"torn or corrupt checkpoint")
                    if n in wanted:
                        staged.append((n, arr))
                if manifest is not None:
                    missing = set(manifest) - set(data.files)
                    if missing:
                        _stats.count("checkpoint.crc_failures")
                        raise CheckpointCorruptError(
                            f"{dirname}: manifest lists "
                            f"{sorted(missing)} but state.npz lacks "
                            f"them")
        except CheckpointCorruptError:
            raise
        except Exception as e:
            # a torn .npz fails INSIDE numpy/zipfile (BadZipFile, EOF,
            # bad zip CRC) before our manifest check even runs
            _stats.count("checkpoint.crc_failures")
            raise CheckpointCorruptError(
                f"{dirname}: state.npz unreadable ({e}) — torn or "
                f"corrupt checkpoint") from e
        # validation passed over the WHOLE archive before any mutation
        for n, arr in staged:
            scope.set(n, jnp.asarray(arr))
    else:
        load_persistables(executor, dirname, program,
                          filename="state.npz", scope=scope)
    _stats.count("checkpoint.restores")
    _stats.observe("checkpoint.restore_ms",
                   (time.perf_counter() - t0) * 1e3)
    return meta


# ---------------------------------------------------------------------------
# async writers: non-daemon + error box + join-at-exit
# ---------------------------------------------------------------------------

_LIVE_WRITERS = []          # CheckpointHandles with a running thread
_writers_lock = threading.Lock()


def _track(handle):
    with _writers_lock:
        _LIVE_WRITERS.append(handle)


def _untrack(handle):
    with _writers_lock:
        try:
            _LIVE_WRITERS.remove(handle)
        except ValueError:
            pass


@atexit.register
def _join_writers_at_exit():
    """A daemon writer dying mid-write at interpreter exit is exactly
    the torn-checkpoint bug; join every outstanding writer instead and
    surface swallowed errors as a warning (raising is useless here)."""
    with _writers_lock:
        pending = list(_LIVE_WRITERS)
    for h in pending:
        try:
            h.wait()
        except BaseException as e:          # noqa: BLE001 — exit path
            warnings.warn(f"async checkpoint write failed: {e!r}")


class CheckpointHandle:
    """Completion barrier for an (async) checkpoint save. `wait()` joins
    the writer and RE-RAISES anything the write path threw — an async
    save error is never silently swallowed."""

    def __init__(self, thread=None, error_box=None):
        self._thread = thread
        # `error_box or []` would DROP the writer's box while it is
        # still empty — the exact silently-swallowed-error bug this
        # handle exists to fix
        self._error_box = error_box if error_box is not None else []

    def wait(self):
        if self._thread is not None:
            self._thread.join()
        _untrack(self)
        if self._error_box:
            raise self._error_box[0]
        return True

    result = wait
    join = wait         # Thread-API compat (save_checkpoint_async < fmt 2)

    def done(self):
        return self._thread is None or not self._thread.is_alive()


def save_checkpoint_async(executor, dirname, main_program=None, step=0,
                          extra=None, scope=None):
    """Async save: snapshot to host on the CALLING thread (orbax-style),
    file I/O in a non-daemon background thread. Returns a
    CheckpointHandle — `wait()` is the completion barrier and re-raises
    any write error; un-waited handles are joined at interpreter exit."""
    scope = scope or global_scope()
    program = main_program or default_main_program()
    snapshot = _snapshot(scope, program)
    meta = {"step": int(step), "extra": extra or {}}
    err_box = []
    handle = CheckpointHandle(None, err_box)

    def _write():
        try:
            _write_state(dirname, snapshot, meta)
        except BaseException as e:      # surfaced at .wait()/atexit
            _stats.count("checkpoint.write_failures")
            err_box.append(e)

    t = threading.Thread(target=_write, daemon=False,
                         name=f"ckpt-writer-{os.path.basename(dirname)}")
    handle._thread = t
    _track(handle)
    t.start()
    return handle


# ---------------------------------------------------------------------------
# Sharded checkpoint (per-device-shard files keyed by NamedSharding)
# ---------------------------------------------------------------------------

def _safe_name(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _spec_to_json(spec):
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append(list(e))
    return out


def _spec_from_json(entries):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def save_checkpoint_sharded(executor, dirname, main_program=None, step=0,
                            extra=None, async_save=False, scope=None):
    """Write every persistable as per-shard .npy files.

    A var with a non-trivial NamedSharding contributes one file per unique
    device shard (its global index recorded in index.json); replicated vars
    contribute one file. Device->host transfers happen synchronously (the
    arrays may be donated by the next step); file IO runs in a background
    non-daemon thread when async_save=True. Returns a CheckpointHandle —
    call .wait() as the completion barrier before relying on the
    checkpoint. Each shard file lands atomically with a CRC32 recorded in
    index.json, which is itself written LAST and atomically: its presence
    is the directory-level commit marker.
    """
    from jax.sharding import NamedSharding

    scope = scope or global_scope()
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]

    index = {}
    payloads = []  # (relpath, np.ndarray)
    for n in sorted(set(names)):
        val = scope.get(n)
        if val is None:
            continue
        sharding = getattr(val, "sharding", None)
        entry = {"shape": [int(s) for s in val.shape],
                 "dtype": str(np.dtype(val.dtype)), "shards": []}
        sharded = (isinstance(sharding, NamedSharding)
                   and any(e is not None for e in tuple(sharding.spec))
                   and hasattr(val, "addressable_shards"))
        if sharded:
            entry["spec"] = _spec_to_json(sharding.spec)
            seen = set()
            for sh in val.addressable_shards:
                start = tuple(0 if s.start is None else int(s.start)
                              for s in sh.index)
                if start in seen:
                    continue  # replicated copy of the same shard
                seen.add(start)
                rel = f"shards/{_safe_name(n)}--{len(entry['shards'])}.npy"
                data = np.asarray(sh.data)
                entry["shards"].append({"file": rel, "start": list(start),
                                        "shape": list(data.shape),
                                        "crc32": _crc32(data)})
                payloads.append((rel, data))
        else:
            rel = f"shards/{_safe_name(n)}--full.npy"
            data = np.asarray(val)
            entry["shards"].append({"file": rel,
                                    "start": [0] * data.ndim,
                                    "shape": list(data.shape),
                                    "crc32": _crc32(data)})
            payloads.append((rel, data))
        index[n] = entry

    meta = {"step": int(step), "extra": extra or {},
            "format": CHECKPOINT_FORMAT}
    err_box = []
    handle = CheckpointHandle(None, err_box)

    def _write():
        try:
            t0 = time.perf_counter()
            os.makedirs(os.path.join(dirname, "shards"), exist_ok=True)
            for rel, data in payloads:
                _atomic_write(os.path.join(dirname, rel),
                              lambda f, d=data: np.save(f, d), "shard")
            # index written LAST + atomically: its presence marks a
            # complete checkpoint (the directory-level commit marker)
            _atomic_write(
                os.path.join(dirname, "index.json"),
                lambda f: f.write(json.dumps(
                    {"meta": meta, "vars": index}).encode()), "index")
            _stats.count("checkpoint.saves")
            _stats.observe("checkpoint.save_ms",
                           (time.perf_counter() - t0) * 1e3)
        except BaseException as e:  # surfaced at .wait()
            _stats.count("checkpoint.write_failures")
            err_box.append(e)

    if async_save:
        t = threading.Thread(target=_write, daemon=False,
                             name="ckpt-shard-writer")
        handle._thread = t
        _track(handle)
        t.start()
        return handle
    _write()
    if err_box:
        raise err_box[0]
    return handle


def load_checkpoint_sharded(executor, dirname, main_program=None, mesh=None,
                            var_names=None, scope=None, validate=True):
    """Restore from a sharded checkpoint. Assembles each var from its shard
    files (bitwise) and places it back: with `mesh` given, vars that were
    saved sharded are device_put with their recorded PartitionSpec on that
    mesh; otherwise they land replicated/unsharded. var_names restores a
    subset (partial restore). Shard CRCs recorded by a format-2 save are
    verified before anything lands in the scope (validate=False skips).
    Returns the meta dict ({step, extra})."""
    import jax
    from jax.sharding import NamedSharding

    scope = scope or global_scope()
    t0 = time.perf_counter()
    index_path = os.path.join(dirname, "index.json")
    if not os.path.exists(index_path):
        raise FileNotFoundError(
            f"{index_path} not found — incomplete or missing checkpoint")
    with open(index_path) as f:
        blob = json.load(f)
    wanted = set(var_names) if var_names is not None else None
    staged = []
    for n, entry in blob["vars"].items():
        if wanted is not None and n not in wanted:
            continue
        full = np.empty(entry["shape"], dtype=np.dtype(entry["dtype"]))
        for sh in entry["shards"]:
            data = np.load(os.path.join(dirname, sh["file"]))
            if validate and "crc32" in sh and _crc32(data) != sh["crc32"]:
                _stats.count("checkpoint.crc_failures")
                raise CheckpointCorruptError(
                    f"{dirname}: CRC mismatch in shard {sh['file']} of "
                    f"'{n}' — torn or corrupt checkpoint")
            idx = tuple(slice(st, st + ln)
                        for st, ln in zip(sh["start"], data.shape))
            full[idx] = data
        staged.append((n, entry, full))
    # validation happened above, BEFORE any scope mutation: a corrupt
    # checkpoint must not leave the scope half-restored
    for n, entry, full in staged:
        if mesh is not None and "spec" in entry:
            arr = jax.device_put(
                full, NamedSharding(mesh, _spec_from_json(entry["spec"])))
        else:
            arr = jax.numpy.asarray(full)
        scope.set(n, arr)
    _stats.count("checkpoint.restores")
    _stats.observe("checkpoint.restore_ms",
                   (time.perf_counter() - t0) * 1e3)
    return blob["meta"]
