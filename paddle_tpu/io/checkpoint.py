"""Full-training-state checkpoint/resume.

Parity: fluid checkpointing (io.py save/load_persistables + trainer state) —
persistables include optimizer accumulators, LR counters and batch-norm
stats, so save/load_checkpoint round-trips a training run exactly.
Sharded/async variants for big models use orbax when available.
"""

import json
import os

import numpy as np

from ..core.framework import default_main_program
from ..core.executor import global_scope
from .state import save_persistables, load_persistables


def save_checkpoint(executor, dirname, main_program=None, step=0, extra=None):
    os.makedirs(dirname, exist_ok=True)
    save_persistables(executor, dirname, main_program, filename="state.npz")
    meta = {"step": int(step), "extra": extra or {}}
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta, f)


def load_checkpoint(executor, dirname, main_program=None):
    load_persistables(executor, dirname, main_program, filename="state.npz")
    meta_path = os.path.join(dirname, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            return json.load(f)
    return {"step": 0, "extra": {}}


def save_checkpoint_async(executor, dirname, main_program=None, step=0):
    """Async save: snapshot to host in a thread (orbax-style async)."""
    import threading
    scope = global_scope()
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]
    snapshot = {n: np.asarray(scope.get(n)) for n in names
                if scope.get(n) is not None}

    def _write():
        os.makedirs(dirname, exist_ok=True)
        np.savez(os.path.join(dirname, "state.npz"), **snapshot)
        with open(os.path.join(dirname, "meta.json"), "w") as f:
            json.dump({"step": int(step), "extra": {}}, f)

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    return t
