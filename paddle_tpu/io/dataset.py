"""Dataset ingestion: file-to-step training without Python-loop feeding.

Parity: python/paddle/fluid/dataset.py (DatasetFactory:21,
InMemoryDataset:269, QueueDataset:613, FileInstantDataset:690,
BoxPSDataset:725) + the C++ DataFeed stack it drives
(paddle/fluid/framework/data_feed.cc). This is how the reference trains
CTR-scale models (the DeepFM config in BASELINE.json) straight from
file lists: `exe.train_from_dataset(program, dataset)`.

TPU-native re-design (not a translation):
- File parsing is native: csrc/dataset_feed.cc threads parse the
  MultiSlot text format (optionally through a UNIX `pipe_command`)
  into flat per-slot value+length columns, off the GIL. A pure-python
  parser backs it up where the toolchain is missing.
- The reference's trainer_factory.py / device_worker.py thread army
  (N HogwildWorkers each running the op list against a feed queue) is
  design-deleted: the whole train step is ONE donated XLA executable,
  so `train_from_dataset` is a host loop handing static-shape batches
  to that executable. Host threads still matter for PARSING — that is
  where thread_num goes.
- Raggedness (lod_level > 0 slots) follows SURVEY §1 decision 4: the
  padded `(batch, max_len)` tensor feeds the slot's own var name, and
  the per-instance lengths feed `<name>_seq_len` when the program
  declares such a var (the framework-wide explicit-length convention;
  LoD offsets never ride inside tensors).
- Batches keep ONE static shape per dataset (sparse slots pad to the
  dataset-wide max for InMemoryDataset, power-of-2 buckets for the
  streaming QueueDataset). One shape = one XLA compile; only the tail
  batch — which the reference also runs at its natural size — gets its
  own jit-cache entry, and `drop_last` removes even that.
"""

import ctypes
import subprocess
import threading

import numpy as np

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset",
           "FileInstantDataset", "BoxPSDataset", "DataFeedDesc"]

_df_lib = None
_df_lock = threading.Lock()


def _load_df_lib():
    """Build-and-dlopen csrc/dataset_feed.cc; None if unavailable."""
    global _df_lib
    with _df_lock:
        if _df_lib is not None:
            return _df_lib or None
        try:
            from ..utils.native import build_and_load
            lib = build_and_load("dataset_feed.cc", "libdatasetfeed.so")
        except Exception:
            _df_lib = False
            return None
        lib.df_create.restype = ctypes.c_void_p
        lib.df_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.df_add_slot.restype = ctypes.c_int
        lib.df_add_slot.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p, ctypes.c_int]
        lib.df_parse_files.restype = ctypes.c_int64
        lib.df_parse_files.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_int]
        lib.df_num_instances.restype = ctypes.c_int64
        lib.df_num_instances.argtypes = [ctypes.c_void_p]
        lib.df_slot_vals_count.restype = ctypes.c_int64
        lib.df_slot_vals_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.df_copy_slot.restype = ctypes.c_int
        lib.df_copy_slot.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int32)]
        lib.df_copy_ins_ids.restype = ctypes.c_int
        lib.df_copy_ins_ids.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.df_clear.argtypes = [ctypes.c_void_p]
        lib.df_last_error.restype = ctypes.c_char_p
        lib.df_last_error.argtypes = [ctypes.c_void_p]
        lib.df_destroy.argtypes = [ctypes.c_void_p]
        _df_lib = lib
        return lib


def _fnv1a(data):
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _parse_files_python(slots, files, pipe_command, parse_ins_id,
                        parse_content):
    """Pure-python fallback for the native parser (same format, same
    errors; reference: data_feed.cc ParseOneInstance)."""
    cols = [([], []) for _ in slots]        # (vals, lens) per slot
    ins_ids = []
    for path in files:
        if pipe_command and pipe_command != "cat":
            with open(path, "rb") as fin:
                proc = subprocess.run(["/bin/sh", "-c", pipe_command],
                                      stdin=fin, capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pipe command {pipe_command!r} failed rc="
                    f"{proc.returncode} for {path}: "
                    f"{proc.stderr.decode(errors='replace')[:200]}")
            lines = proc.stdout.decode().splitlines()
        else:
            with open(path) as fin:
                lines = fin.read().splitlines()
        for line in lines:
            toks = line.split()
            if not toks:
                continue
            pos = 0
            for flag, sink in ((parse_ins_id, ins_ids),
                               (parse_content, None)):
                if flag:
                    if toks[pos] != "1":
                        raise ValueError(f"bad tagged field in line: "
                                         f"{line[:120]!r}")
                    if sink is not None:
                        sink.append(_fnv1a(toks[pos + 1].encode()))
                    pos += 2
            for i, s in enumerate(slots):
                num = int(toks[pos])
                pos += 1
                if num <= 0:
                    raise ValueError(
                        f"slot '{s['name']}': the number of ids can not "
                        f"be zero, you need padding it in data generator")
                if pos + num > len(toks):
                    raise ValueError(
                        f"slot '{s['name']}': truncated values "
                        f"(declared {num}, line has {len(toks) - pos})")
                if s["type"] == "float":
                    conv = float
                else:
                    def conv(t):
                        # uint64 feasigns >= 2^63 wrap to int64, same as
                        # the native parser's static_cast<int64_t>
                        v = int(t)
                        return v - (1 << 64) if v >= (1 << 63) else v
                cols[i][0].extend(conv(t) for t in toks[pos:pos + num])
                cols[i][1].append(num)
                pos += num
    out = []
    for (vals, lens), s in zip(cols, slots):
        dt = np.float32 if s["type"] == "float" else np.int64
        out.append((np.asarray(vals, dt), np.asarray(lens, np.int32)))
    ids = (np.asarray(ins_ids, np.uint64) if parse_ins_id
           else np.zeros(0, np.uint64))
    return out, ids


def _parse_files_native(slots, files, pipe_command, parse_ins_id,
                        parse_content, n_threads):
    lib = _load_df_lib()
    ctx = lib.df_create(1 if parse_ins_id else 0, 1 if parse_content else 0)
    try:
        for s in slots:
            t = b"uint64" if s["type"] == "uint64" else b"float"
            lib.df_add_slot(ctx, s["name"].encode(), t, 1 if s["is_dense"]
                            else 0)
        blob = b"".join(f.encode() + b"\0" for f in files)
        n = lib.df_parse_files(ctx, blob, len(files),
                               (pipe_command or "").encode(),
                               max(1, n_threads))
        if n < 0:
            raise RuntimeError("dataset parse failed: "
                               + lib.df_last_error(ctx).decode())
        n_ins = lib.df_num_instances(ctx)
        out = []
        for i, s in enumerate(slots):
            nvals = lib.df_slot_vals_count(ctx, i)
            dt = np.float32 if s["type"] == "float" else np.int64
            vals = np.empty(nvals, dt)
            lens = np.empty(n_ins, np.int32)
            lib.df_copy_slot(ctx, i, vals.ctypes.data_as(ctypes.c_void_p),
                             lens.ctypes.data_as(
                                 ctypes.POINTER(ctypes.c_int32)))
            out.append((vals, lens))
        ids = np.zeros(0, np.uint64)
        if parse_ins_id:
            ids = np.empty(n_ins, np.uint64)
            lib.df_copy_ins_ids(ctx, ids.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64)))
        return out, ids
    finally:
        lib.df_destroy(ctx)


def _parse_files(slots, files, pipe_command, parse_ins_id=False,
                 parse_content=False, n_threads=1):
    if _load_df_lib() is not None:
        return _parse_files_native(slots, files, pipe_command,
                                   parse_ins_id, parse_content, n_threads)
    return _parse_files_python(slots, files, pipe_command, parse_ins_id,
                               parse_content)


class DataFeedDesc:
    """Parity: python/paddle/fluid/data_feed_desc.py — a text-protobuf
    DataFeedDesc the user can tweak before handing to a dataset. Backed
    by a plain dict here (no protobuf runtime in the TPU build)."""

    def __init__(self, proto_desc_text):
        self._desc = _parse_text_desc(proto_desc_text)

    def set_batch_size(self, batch_size):
        self._desc["batch_size"] = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for s in self._desc["slots"]:
            if s["name"] in dense_slots_name:
                s["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        for s in self._desc["slots"]:
            if s["name"] in use_slots_name:
                s["is_used"] = True

    def desc(self):
        return _format_text_desc(self._desc)


def _parse_text_desc(text):
    """Minimal text-format protobuf reader for DataFeedDesc messages."""
    desc = {"name": "MultiSlotDataFeed", "batch_size": 32,
            "pipe_command": "cat", "slots": []}
    stack = [desc]
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.endswith("{"):
            key = line[:-1].strip()
            if key == "slots":
                slot = {"name": "", "type": "float", "is_dense": False,
                        "is_used": False, "shape": []}
                desc["slots"].append(slot)
                stack.append(slot)
            else:                       # multi_slot_desc wrapper
                stack.append(stack[-1])
        elif line == "}":
            stack.pop()
        elif ":" in line:
            key, val = line.split(":", 1)
            key, val = key.strip(), val.strip()
            if val.startswith('"'):
                val = val.strip('"')
            elif val in ("true", "false"):
                val = val == "true"
            else:
                try:
                    val = int(val)
                except ValueError:
                    pass
            tgt = stack[-1]
            if key == "shape":
                tgt.setdefault("shape", []).append(val)
            elif key in ("name", "type", "is_dense", "is_used",
                         "batch_size", "pipe_command"):
                tgt[key] = val
    return desc


def _format_text_desc(desc):
    lines = [f'name: "{desc["name"]}"',
             f'batch_size: {desc["batch_size"]}',
             f'pipe_command: "{desc["pipe_command"]}"',
             "multi_slot_desc {"]
    for s in desc["slots"]:
        lines.append("  slots {")
        lines.append(f'    name: "{s["name"]}"')
        lines.append(f'    type: "{s["type"]}"')
        lines.append(f'    is_dense: {"true" if s["is_dense"] else "false"}')
        lines.append(f'    is_used: {"true" if s["is_used"] else "false"}')
        for d in s.get("shape", []):
            lines.append(f"    shape: {d}")
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"


class DatasetFactory:
    """Parity: fluid.DatasetFactory (dataset.py:21)."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            return globals()[datafeed_class]()
        except KeyError:
            raise ValueError("datafeed class %s does not exist"
                             % datafeed_class)


class DatasetBase:
    """Parity: fluid.dataset.DatasetBase (dataset.py:63)."""

    _feed_name = "MultiSlotDataFeed"

    def __init__(self):
        self.pipe_command = "cat"
        self.batch_size = 32
        self.thread_num = 0
        self.filelist = []
        self.slots = []             # {name, type, is_dense, shape, lod_level}
        self.drop_last = False
        self._shuffle_seed = None   # set for deterministic shuffles

    # -- knob setters (names + semantics from the reference) -----------
    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.slots = []
        for var in var_list:
            if var.dtype == "float32":
                t = "float"
            elif var.dtype == "int64":
                t = "uint64"
            else:
                raise ValueError(
                    "Currently, fluid.dataset only supports dtype=float32 "
                    "and dtype=int64")
            shape = [int(s) for s in var.shape if int(s) > 0]
            self.slots.append({
                "name": var.name, "type": t,
                "is_dense": var.lod_level == 0, "shape": shape,
                "is_used": True, "lod_level": var.lod_level,
            })

    def set_hdfs_config(self, fs_name, fs_ugi):
        # no HDFS client in the TPU image; record for desc parity
        self.fs_name, self.fs_ugi = fs_name, fs_ugi

    def set_fea_eval(self, record_candidate_size, fea_eval=True):
        self.fea_eval = fea_eval
        self._record_candidate_size = record_candidate_size

    def slots_shuffle(self, slots):
        raise NotImplementedError(
            "slots_shuffle is only supported by InMemoryDataset")

    def set_shuffle_seed(self, seed):
        """TPU-native extension: deterministic shuffles (the reference
        seeds its shuffle from std::random_device)."""
        self._shuffle_seed = seed

    # ------------------------------------------------------------------
    def _prepare_to_run(self):
        if not self.slots:
            raise RuntimeError("dataset.set_use_var was never called")
        if self.thread_num > len(self.filelist):
            self.thread_num = len(self.filelist)
        if self.thread_num <= 0:
            self.thread_num = 1

    def _finish_to_run(self):
        pass

    def desc(self):
        return _format_text_desc({
            "name": self._feed_name, "batch_size": self.batch_size,
            "pipe_command": self.pipe_command, "slots": self.slots})

    # -- batch assembly shared by the subclasses -----------------------
    def _columns_to_batches(self, cols, order, pad_caps):
        """Yield feed dicts of `batch_size` instances following `order`.

        Dense slots gather to (B, *shape); sparse slots pad to the
        static cap and also emit `<name>_seq_len`. The tail keeps its
        natural size (its own jit cache entry) unless drop_last."""
        bs = self.batch_size
        n = len(order)
        starts = []
        for s, (vals, lens) in zip(self.slots, cols):
            if s["is_dense"] and len(lens):
                d = int(np.prod(s["shape"])) if s["shape"] else 1
                if not (lens == d).all():
                    bad = int(np.argmax(lens != d))
                    raise ValueError(
                        f"dense slot '{s['name']}' expects {d} values per "
                        f"instance (shape {s['shape']}), but instance "
                        f"{bad} has {int(lens[bad])}")
            off = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=off[1:])
            starts.append(off)
        for b0 in range(0, n, bs):
            idx = order[b0:b0 + bs]
            if len(idx) < bs and self.drop_last:
                return
            feed = {}
            for s, (vals, lens), off in zip(self.slots, cols, starts):
                take_lens = lens[idx]
                if s["is_dense"]:
                    d = int(np.prod(s["shape"])) if s["shape"] else 1
                    gather = np.empty((len(idx), d), vals.dtype)
                    for r, i in enumerate(idx):
                        gather[r] = vals[off[i]:off[i] + d]
                    feed[s["name"]] = gather.reshape(
                        (len(idx),) + tuple(s["shape"] or [1]))
                else:
                    cap = pad_caps[s["name"]]
                    padded = np.zeros((len(idx), cap), vals.dtype)
                    for r, i in enumerate(idx):
                        row = vals[off[i]:off[i + 1]]
                        padded[r, :len(row)] = row
                    feed[s["name"]] = padded
                    feed[s["name"] + "_seq_len"] = take_lens.reshape(-1, 1)
            yield feed


class InMemoryDataset(DatasetBase):
    """Parity: fluid.InMemoryDataset (dataset.py:269): load all files
    into host memory, shuffle (locally or across workers), train."""

    _feed_name = "MultiSlotInMemoryDataFeed"

    def __init__(self):
        super().__init__()
        self.queue_num = None
        self.parse_ins_id = False
        self.parse_content = False
        self.merge_by_lineid = False
        self.fleet_send_batch_size = None
        self._cols = None          # [(vals, lens)] per slot
        self._ins_ids = None
        self._order = None
        self._preload_thread = None

    # -- knob setters ---------------------------------------------------
    def set_queue_num(self, queue_num):
        self.queue_num = queue_num

    def set_parse_ins_id(self, parse_ins_id):
        self.parse_ins_id = parse_ins_id

    def set_parse_content(self, parse_content):
        self.parse_content = parse_content

    def set_fleet_send_batch_size(self, fleet_send_batch_size):
        self.fleet_send_batch_size = fleet_send_batch_size

    def set_merge_by_lineid(self, var_list=None, erase_duplicate_feas=True,
                            min_merge_size=2, keep_unmerged_ins=True):
        self._merge_slots = ([v.name for v in var_list]
                             if var_list else None)
        self._erase_duplicate = erase_duplicate_feas
        self._min_merge_size = min_merge_size
        self._keep_unmerged = keep_unmerged_ins
        self.merge_by_lineid = True
        self.parse_ins_id = True

    # -- loading --------------------------------------------------------
    def load_into_memory(self):
        self._prepare_to_run()
        self._cols, self._ins_ids = _parse_files(
            self.slots, self.filelist, self.pipe_command,
            self.parse_ins_id, self.parse_content, self.thread_num)
        self._order = np.arange(len(self._cols[0][1]))

    def preload_into_memory(self):
        """Async load (reference: dataset.py:426)."""
        self._prepare_to_run()
        self._preload_thread = threading.Thread(target=self.load_into_memory,
                                                daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self):
        if self._preload_thread is not None:
            self._preload_thread.join()
            self._preload_thread = None

    def release_memory(self):
        self._cols = self._ins_ids = self._order = None

    # -- shuffles -------------------------------------------------------
    def local_shuffle(self):
        self._require_loaded()
        rng = np.random.default_rng(self._shuffle_seed)
        rng.shuffle(self._order)

    def global_shuffle(self, fleet=None):
        """Across-worker shuffle. The reference redistributes records
        over the fleet via pserver client2client messages; the TPU
        re-expression:

        - multi-PROCESS fleet (jax.process_count() > 1): all-gather the
          columns over DCN, then every process keeps its deterministic
          hash shard — instance -> worker by ins_id hash (keeps
          merge_by_lineid groups together) or by global index. Same
          result as the reference's redistribution (each record lands
          on exactly one worker, regardless of which worker loaded it)
          at the cost of a transient full copy per host.
        - single-process simulated fleets (worker_num > 1 from a role
          maker, one jax process — the CPU-mesh workflow): no channel
          exists, so every worker must have loaded the SAME filelist;
          the hash partition then selects this worker's shard.
        """
        self._require_loaded()
        nworker, wid = 1, 0
        if fleet is not None:
            fleet.barrier_worker()
            nworker, wid = fleet.worker_num(), fleet.worker_index()
        if nworker > 1:
            import jax
            if jax.process_count() > 1:
                self._allgather_columns()
            if (self.parse_ins_id and self._ins_ids is not None
                    and len(self._ins_ids)):
                keys = self._ins_ids.astype(np.uint64)
            else:
                # splitmix64 over the global index: cheap, uniform
                keys = np.arange(len(self._order), dtype=np.uint64)
                keys = (keys + np.uint64(0x9E3779B97F4A7C15))
                keys ^= keys >> np.uint64(30)
                keys = keys * np.uint64(0xBF58476D1CE4E5B9)
                keys ^= keys >> np.uint64(27)
            mine = np.where(keys % np.uint64(nworker) == np.uint64(wid))[0]
            self._select_instances(mine)
        rng = np.random.default_rng(self._shuffle_seed)
        rng.shuffle(self._order)
        if self.merge_by_lineid:
            self._merge_by_lineid_now()
        if fleet is not None:
            fleet.barrier_worker()

    def _allgather_columns(self):
        """Exchange every process's loaded instances over DCN so each
        host sees the union (in rank order) before hash-partitioning.
        Ragged columns are padded to the max size, gathered with
        multihost_utils.process_allgather, and trimmed back."""
        from jax.experimental import multihost_utils

        def gather_ragged(arr):
            # 8-byte dtypes ride as uint32 halves: without jax_enable_x64
            # the gather would silently truncate int64/uint64 feasigns
            orig = arr.dtype
            if arr.dtype.itemsize == 8:
                arr = np.ascontiguousarray(arr).view(np.uint32)
            n = np.asarray([arr.shape[0]], np.int32)
            counts = np.asarray(
                multihost_utils.process_allgather(n)).reshape(-1)
            cap = int(counts.max())
            padded = np.zeros((cap,), arr.dtype)
            padded[:arr.shape[0]] = arr
            all_rows = np.asarray(multihost_utils.process_allgather(padded))
            out = np.concatenate([all_rows[r, :counts[r]]
                                  for r in range(len(counts))])
            return out.view(orig) if orig.itemsize == 8 else out

        # instance order must follow self._order so prior local state
        # (a local_shuffle before global_shuffle) is preserved per rank
        self._apply_order()
        self._cols = [(gather_ragged(vals), gather_ragged(lens))
                      for vals, lens in self._cols]
        if (self.parse_ins_id and self._ins_ids is not None
                and len(self._ins_ids)):
            self._ins_ids = gather_ragged(self._ins_ids)
        self._order = np.arange(len(self._cols[0][1]))

    def _apply_order(self):
        """Materialize self._order into the physical column layout."""
        if self._order is None or np.array_equal(
                self._order, np.arange(len(self._order))):
            return
        self._select_instances(self._order)

    def slots_shuffle(self, slots):
        """Shuffle the VALUES of the named slots across instances while
        other slots stay put (feature-importance debugging; reference:
        dataset.py:117 + fea_eval)."""
        self._require_loaded()
        rng = np.random.default_rng(self._shuffle_seed)
        names = {s["name"]: i for i, s in enumerate(self.slots)}
        for name in slots:
            i = names[name]
            vals, lens = self._cols[i]
            if not (lens == lens[0]).all():
                raise NotImplementedError(
                    "slots_shuffle over ragged slots is unsupported")
            w = int(lens[0])
            perm = rng.permutation(len(lens))
            self._cols[i] = (vals.reshape(-1, w)[perm].reshape(-1), lens)

    # -- sizes ----------------------------------------------------------
    def get_memory_data_size(self, fleet=None):
        self._require_loaded()
        return self._global_sum(len(self._order), fleet)

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    # -- internals ------------------------------------------------------
    def _require_loaded(self):
        if self._cols is None:
            raise RuntimeError("call load_into_memory() first")

    @staticmethod
    def _global_sum(local, fleet):
        import jax
        if fleet is not None and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            import numpy as _np
            gathered = multihost_utils.process_allgather(
                _np.asarray([local], _np.int64))
            return int(gathered.sum())
        return local

    def _select_instances(self, keep_idx):
        """Physically keep only `keep_idx` instances (global_shuffle's
        partition step)."""
        new_cols = []
        for (vals, lens) in self._cols:
            off = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=off[1:])
            parts = [vals[off[i]:off[i + 1]] for i in keep_idx]
            new_cols.append((np.concatenate(parts) if parts
                             else vals[:0], lens[keep_idx]))
        self._cols = new_cols
        if self._ins_ids is not None and len(self._ins_ids):
            self._ins_ids = self._ins_ids[keep_idx]
        self._order = np.arange(len(keep_idx))

    def _merge_by_lineid_now(self):
        """Merge instances sharing an ins_id (reference MergeByInsId:
        listed slots concatenate values — optionally deduped — other
        slots keep the first instance's values)."""
        ids = self._ins_ids
        if ids is None or not len(ids):
            return
        groups = {}
        for row in self._order:
            groups.setdefault(ids[row], []).append(row)
        merge_all = getattr(self, "_merge_slots", None) is None
        merged_cols = [([], []) for _ in self.slots]
        keep_rows = []
        offs = []
        for vals, lens in self._cols:
            off = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=off[1:])
            offs.append(off)
        for gid, rows in groups.items():
            if len(rows) < self._min_merge_size:
                if self._keep_unmerged:
                    keep_rows.extend(rows)
                continue
            for i, s in enumerate(self.slots):
                vals, lens = self._cols[i]
                off = offs[i]
                # dense slots keep their fixed values-per-instance
                # contract: always take the first instance's values
                if not s["is_dense"] and (merge_all
                                          or s["name"] in
                                          self._merge_slots):
                    cat = np.concatenate([vals[off[r]:off[r + 1]]
                                          for r in rows])
                    if self._erase_duplicate:
                        _, first = np.unique(cat, return_index=True)
                        cat = cat[np.sort(first)]
                else:
                    r = rows[0]
                    cat = vals[off[r]:off[r + 1]]
                merged_cols[i][0].append(cat)
                merged_cols[i][1].append(len(cat))
        # rebuild: merged groups first, then kept singles (stable)
        final_cols = []
        for i, s in enumerate(self.slots):
            vals, lens = self._cols[i]
            off = offs[i]
            parts = list(merged_cols[i][0]) + [
                vals[off[r]:off[r + 1]] for r in keep_rows]
            plens = list(merged_cols[i][1]) + [int(lens[r])
                                               for r in keep_rows]
            final_cols.append((
                np.concatenate(parts) if parts else vals[:0],
                np.asarray(plens, np.int32)))
        self._cols = final_cols
        self._ins_ids = None
        self._order = np.arange(len(final_cols[0][1]))

    def _pad_caps(self):
        caps = {}
        for s, (vals, lens) in zip(self.slots, self._cols):
            if not s["is_dense"]:
                caps[s["name"]] = int(lens.max()) if len(lens) else 1
        return caps

    def _iter_batches(self, thread_num=None):
        self._require_loaded()
        yield from self._columns_to_batches(self._cols, self._order,
                                            self._pad_caps())


class QueueDataset(DatasetBase):
    """Parity: fluid.QueueDataset (dataset.py:613): stream files one at
    a time without loading the whole dataset; no shuffles."""

    _feed_name = "MultiSlotDataFeed"

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset does not support local shuffle, "
            "please use InMemoryDataset for local_shuffle")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset does not support global shuffle, "
            "please use InMemoryDataset for global_shuffle")

    def _iter_batches(self, thread_num=None):
        """Stream batches file-by-file with a one-file lookahead parsed
        on a background thread (the native parser releases the GIL, so
        parse overlaps device steps). Instances cross file boundaries;
        sparse slots pad to a power-of-2 bucket to bound recompiles."""
        nt = thread_num or self.thread_num or 1

        def parse(f):
            return _parse_files(self.slots, [f], self.pipe_command,
                                n_threads=nt)

        def start_lookahead(path):
            box = {}

            def work():
                try:
                    box["cols"] = parse(path)[0]
                except Exception as e:     # re-raised on the consumer side
                    box["err"] = e
            th = threading.Thread(target=work, daemon=True)
            th.start()
            return th, box

        pending = None
        carry = None
        for fi, path in enumerate(self.filelist):
            if pending is None:
                cols, _ = parse(path)
            else:
                th, box = pending
                th.join()
                if "err" in box:
                    raise box["err"]
                cols = box["cols"]
            pending = (start_lookahead(self.filelist[fi + 1])
                       if fi + 1 < len(self.filelist) else None)
            if carry is not None:
                cols = [(np.concatenate([cv, v]),
                         np.concatenate([cl, l]))
                        for (cv, cl), (v, l) in zip(carry, cols)]
                carry = None
            n = len(cols[0][1])
            full = (n // self.batch_size) * self.batch_size
            if full:
                order = np.arange(full)
                caps = {}
                for s, (vals, lens) in zip(self.slots, cols):
                    if not s["is_dense"]:
                        m = int(lens[:full].max())
                        caps[s["name"]] = max(1, 1 << (m - 1).bit_length())
                yield from self._columns_to_batches(cols, order, caps)
            if n > full:        # remainder rides into the next file
                keep = np.arange(full, n)
                carry = []
                for vals, lens in cols:
                    off = np.zeros(len(lens) + 1, np.int64)
                    np.cumsum(lens, out=off[1:])
                    carry.append((vals[off[full]:],
                                  lens[keep]))
        if carry is not None and not self.drop_last:
            n = len(carry[0][1])
            caps = {}
            for s, (vals, lens) in zip(self.slots, carry):
                if not s["is_dense"]:
                    m = int(lens.max()) if len(lens) else 1
                    caps[s["name"]] = max(1, 1 << (m - 1).bit_length())
            yield from self._columns_to_batches(carry, np.arange(n), caps)


class FileInstantDataset(QueueDataset):
    """Parity: fluid.FileInstantDataset (dataset.py:690) — streaming
    feed without the queue indirection; same streaming semantics here."""

    _feed_name = "MultiSlotFileInstantDataFeed"

    def local_shuffle(self):
        raise NotImplementedError(
            "FileInstantDataset does not support local shuffle, "
            "please use InMemoryDataset for local_shuffle")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "FileInstantDataset does not support global shuffle, "
            "please use InMemoryDataset for global_shuffle")


class BoxPSDataset(InMemoryDataset):
    """Parity: fluid.BoxPSDataset (dataset.py:725). BoxPS is a GPU
    parameter-server cache; on TPU the params are sharded on-device
    (ZeRO/fsdp), so begin/end_pass are pass-through markers."""

    _feed_name = "MultiSlotInMemoryDataFeed"

    def begin_pass(self):
        pass

    def end_pass(self):
        pass

    def load_into_memory(self):
        super().load_into_memory()

    def preload_into_memory(self):
        super().preload_into_memory()
