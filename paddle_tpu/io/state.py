"""Save/load parameters & persistables.

Parity: python/paddle/fluid/io.py (save_vars/save_params/save_persistables,
load_*). Storage format: one .npy per var under dirname, or a single
combined .npz when filename is given — a portable host-side format (the
reference writes LoDTensor protobufs).
"""

import os

import numpy as np

from ..core.framework import Parameter, default_main_program
from ..core.executor import global_scope


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def _resolve(executor, dirname, main_program, predicate, filename, save):
    program = main_program or default_main_program()
    scope = global_scope()
    names = [v.name for v in program.list_vars() if predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    return program, scope, names


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    scope = global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if (predicate or is_persistable)(v)]
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        val = scope.get(name)
        if val is None:
            continue
        arrays[name] = np.asarray(val)
    if filename is not None:
        np.savez(os.path.join(dirname, filename), **arrays)
    else:
        for name, arr in arrays.items():
            np.save(os.path.join(dirname, name.replace("/", "__") + ".npy"), arr)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    scope = global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if (predicate or is_persistable)(v)]
    if filename is not None:
        data = np.load(os.path.join(dirname, filename)
                       if not filename.endswith(".npz")
                       else os.path.join(dirname, filename), allow_pickle=False)
        for v in vars:
            name = v.name if not isinstance(v, str) else v
            if name in data:
                scope.set(name, jnp.asarray(data[name]))
        return
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        if os.path.exists(path):
            scope.set(name, jnp.asarray(np.load(path)))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename)


def get_parameter_value(para, executor=None):
    return np.asarray(global_scope().get(para.name))


def get_parameter_value_by_name(name, executor=None, program=None):
    return np.asarray(global_scope().get(name))
