"""Save/load parameters & persistables.

Parity: python/paddle/fluid/io.py (save_vars/save_params/save_persistables,
load_*). Storage format: one .npy per var under dirname, or a single
combined .npz when filename is given — a portable host-side format (the
reference writes LoDTensor protobufs).

Robustness: every file save_vars writes lands ATOMICALLY (temp file +
fsync + os.replace) — a crash mid-save leaves the previous file intact,
never a torn .npy/.npz that numpy would half-load. `scope=` selects the
variable store (checkpoint rollback restores into a GuardedTrainer's
private scope, not whatever the global scope happens to be).
"""

import os

import numpy as np

from ..core.framework import Parameter, default_main_program
from ..core.executor import global_scope


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def _fsync_dir(dirname):
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_save(path, writer):
    """temp + fsync + os.replace: the destination either keeps its old
    bytes or atomically becomes the complete new file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(path) or ".")


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if (predicate or is_persistable)(v)]
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        val = scope.get(name)
        if val is None:
            continue
        arrays[name] = np.asarray(val)
    if filename is not None:
        _atomic_save(os.path.join(dirname, filename),
                     lambda f: np.savez(f, **arrays))
    else:
        for name, arr in arrays.items():
            _atomic_save(
                os.path.join(dirname, name.replace("/", "__") + ".npy"),
                lambda f, a=arr: np.save(f, a))


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename, scope=scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename,
                     scope=scope)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if (predicate or is_persistable)(v)]
    if filename is not None:
        data = np.load(os.path.join(dirname, filename)
                       if not filename.endswith(".npz")
                       else os.path.join(dirname, filename), allow_pickle=False)
        for v in vars:
            name = v.name if not isinstance(v, str) else v
            if name in data:
                scope.set(name, jnp.asarray(data[name]))
        return
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        if os.path.exists(path):
            scope.set(name, jnp.asarray(np.load(path)))


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename, scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename,
                     scope=scope)


def get_parameter_value(para, executor=None):
    return np.asarray(global_scope().get(para.name))


def get_parameter_value_by_name(name, executor=None, program=None):
    return np.asarray(global_scope().get(name))
