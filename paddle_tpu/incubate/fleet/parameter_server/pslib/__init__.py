"""Parity shim: incubate/fleet/parameter_server/pslib — the Downpour
pserver runtime (PSLib, DownpourOptimizer, DistributedAdam, the
Server/Worker node descriptors). Non-port; see
parameter_server/__init__.py for the rationale and replacement."""

_MSG = ("{name}: the pslib Downpour parameter-server runtime has no TPU "
        "analog — sparse tables shard over the mesh as ordinary "
        "parameters and the async push/pull is compiled ICI "
        "collectives. Use paddle_tpu.incubate.fleet.collective.fleet; "
        "see parallel/transpiler.py and MIGRATION.md.")

__all__ = ["PSLib", "DownpourOptimizer", "DistributedAdam",
           "Server", "Worker", "DownpourServer", "DownpourWorker"]


def _shim(name):
    class _Shim:
        def __init__(self, *a, **k):
            raise NotImplementedError(_MSG.format(name=name))
    _Shim.__name__ = _Shim.__qualname__ = name
    return _Shim


PSLib = _shim("PSLib")
DownpourOptimizer = _shim("DownpourOptimizer")
DistributedAdam = _shim("DistributedAdam")
Server = _shim("Server")
Worker = _shim("Worker")
DownpourServer = _shim("DownpourServer")
DownpourWorker = _shim("DownpourWorker")
