"""Parity shim: incubate/fleet/parameter_server/distribute_transpiler —
pserver fleet mode; see parameter_server/__init__.py for the non-port
rationale."""

_MSG = ("{name}: parameter-server fleet mode has no TPU analog — "
        "optimizer state shards over the mesh instead (ZeRO/fsdp). Use "
        "paddle_tpu.incubate.fleet.collective.fleet with a "
        "DistributedStrategy; see parallel/transpiler.py and "
        "MIGRATION.md.")

__all__ = ["DistributedTranspiler", "TranspilerOptimizer"]


class DistributedTranspiler:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG.format(name="DistributedTranspiler"))


class TranspilerOptimizer:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG.format(name="TranspilerOptimizer"))
