"""Parity shims: python/paddle/fluid/incubate/fleet/parameter_server/ —
documented NON-PORT of the parameter-server fleet modes.

Both halves (distribute_transpiler mode and the pslib Downpour runtime)
exist to spread a sparse/async CPU training job over pserver processes.
A TPU pod has no pserver tier: parameters (including huge embeddings)
shard over the device mesh as ordinary arrays, optimizer state shards
with ZeRO/fsdp (parallel/transpiler.py documents the re-expression),
and the async push/pull becomes compiled ICI collectives. Use

    from paddle_tpu.incubate.fleet.collective import fleet

with a DistributedStrategy instead; MIGRATION.md maps the pserver
config knobs. The classes here are import-compatible and raise with
that guidance when constructed (the launch half lives in
distributed/launch_ps.py, same contract).
"""

from . import distribute_transpiler  # noqa: F401
from . import pslib  # noqa: F401
