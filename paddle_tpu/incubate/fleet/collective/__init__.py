"""Parity: python/paddle/fluid/incubate/fleet/collective/__init__.py —
the collective-mode fleet singleton and its optimizer wrappers
(implementation: parallel/fleet.py; GSPMD inserts the collectives the
reference's transpiled allreduce ops expressed)."""

from ....parallel.fleet import (  # noqa: F401
    Collective, CollectiveOpBasedOptimizer, CollectiveOptimizer,
    DistFCConfig, DistributedStrategy, LambConfig, fleet)

__all__ = ["LambConfig", "DistFCConfig", "Collective",
           "DistributedStrategy", "CollectiveOpBasedOptimizer",
           "CollectiveOptimizer", "fleet"]
