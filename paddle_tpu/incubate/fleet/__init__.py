"""incubate.fleet — reference import-path mirror onto parallel.fleet.

Parity: python/paddle/fluid/incubate/fleet/. The implementation lives in
paddle_tpu/parallel/fleet.py (one mesh-first Fleet; collective mode is
native, pserver modes are documented non-ports).
"""

from . import base  # noqa: F401
from . import collective  # noqa: F401
from . import parameter_server  # noqa: F401
from . import utils  # noqa: F401
