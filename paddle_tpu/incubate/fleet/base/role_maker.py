"""Parity: python/paddle/fluid/incubate/fleet/base/role_maker.py —
re-exports of the env-driven role makers (parallel/fleet.py)."""

from ....parallel.fleet import (  # noqa: F401
    MPISymetricRoleMaker, PaddleCloudRoleMaker, Role, RoleMakerBase,
    UserDefinedCollectiveRoleMaker, UserDefinedRoleMaker)

__all__ = ["Role", "RoleMakerBase", "MPISymetricRoleMaker",
           "UserDefinedRoleMaker", "UserDefinedCollectiveRoleMaker",
           "PaddleCloudRoleMaker"]
