"""Parity: python/paddle/fluid/incubate/fleet/base/fleet_base.py —
re-exports of the mesh-first implementations (parallel/fleet.py)."""

from ....parallel.fleet import (  # noqa: F401
    DistributedOptimizer, Fleet, Mode)

__all__ = ["Mode", "Fleet", "DistributedOptimizer"]
