"""Parity: python/paddle/fluid/incubate/fleet/utils/hdfs.py — the same
shell-out client as contrib.utils (one implementation, two reference
import paths)."""

from ....contrib.utils.hdfs_utils import HDFSClient  # noqa: F401

__all__ = ["HDFSClient"]
