"""Fleet utility grab-bag.

Parity: python/paddle/fluid/incubate/fleet/utils/fleet_util.py:46
(``FleetUtil``). The portable methods are implemented; the pslib
Downpour-table methods (xbox donefiles, cache models, table push/pull)
raise with guidance — their job (publishing pserver table shards) does
not exist on TPU, where checkpoints are whole-state Orbax/io.state
saves (io/checkpoint.py).
"""

import logging
import re

import numpy as np

from ....parallel.fleet import fleet

__all__ = ["FleetUtil"]

_logger = logging.getLogger("FleetUtil")


def _brace_expand(spec):
    """'{a..b}' -> list of values, zero-padded like bash brace expansion
    (the reference shells out to ``echo -n {20190720..20190729}``)."""
    spec = str(spec).strip()
    m = re.fullmatch(r"\{(\d+)\.\.(\d+)\}", spec)
    if m:
        lo, hi = m.group(1), m.group(2)
        width = len(lo) if lo.startswith("0") else 0
        return [str(v).zfill(width) for v in range(int(lo), int(hi) + 1)]
    return spec.split()


def _allreduce_sum(x):
    """Sum a host numpy array over fleet processes (identity when
    single-process; the reference uses an MPI Allreduce)."""
    import jax
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x)).sum(0)


class FleetUtil:
    def rank0_print(self, s):
        if fleet.worker_index() == 0:
            print(s)

    def rank0_info(self, s):
        if fleet.worker_index() == 0:
            _logger.info(s)

    def rank0_error(self, s):
        if fleet.worker_index() == 0:
            _logger.error(s)

    def set_zero(self, var_name, scope=None, place=None,
                 param_type="int64"):
        """Zero a scope variable in place (ref fleet_util.py:107)."""
        from ....core.executor import global_scope
        scope = scope or global_scope()
        cur = np.asarray(scope.get(var_name))
        scope.set(var_name, np.zeros(cur.shape, dtype=param_type))

    def get_global_auc(self, scope=None, stat_pos="stat_pos",
                       stat_neg="stat_neg"):
        """Exact AUC from the all-reduced auc stat buckets
        (ref fleet_util.py:172-246: reversed-bucket trapezoid walk).
        Works on the (n,) stat arrays our auc layer keeps (the reference
        stores them (1, n))."""
        from ....core.executor import global_scope
        scope = scope or global_scope()
        raw_pos, raw_neg = scope.get(stat_pos), scope.get(stat_neg)
        if raw_pos is None or raw_neg is None:
            self.rank0_print("not found auc bucket")
            return None
        pos = np.asarray(raw_pos, dtype=np.float64)
        neg = np.asarray(raw_neg, dtype=np.float64)
        fleet.barrier_worker()
        pos = _allreduce_sum(pos.reshape(-1))
        neg = _allreduce_sum(neg.reshape(-1))
        # reversed walk: high scores first, trapezoid area over the
        # (fp, tp) curve, normalized by pos*neg
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        area = float(np.sum((fp - np.concatenate([[0.0], fp[:-1]]))
                            * (np.concatenate([[0.0], tp[:-1]]) + tp) / 2))
        total_pos, total_neg = float(tp[-1]), float(fp[-1])
        fleet.barrier_worker()
        if total_pos * total_neg == 0 or total_pos + total_neg == 0:
            return 0.5
        return area / (total_pos * total_neg)

    def print_global_auc(self, scope=None, stat_pos="stat_pos",
                         stat_neg="stat_neg", print_prefix=""):
        auc_value = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc_value}")

    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """Split a training day into pass buckets (ref :1073-1133);
        brace specs like '{0..23}' expand as in bash."""
        hours = _brace_expand(hours)
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left, right = int(hours[0]), int(hours[-1])

        split_path = []
        start = 0
        for _ in range(splits_per_day):
            h, m = start // 60, start % 60
            start += split_interval
            if h < left or h > right:
                continue
            split_path.append(f"{h:02d}" if is_data_hourly_placed
                              else f"{h:02d}{m:02d}")

        online_pass_interval = []
        start = 0
        for _ in range(pass_per_day):
            chunk = split_path[start:start + split_per_pass]
            if not chunk:
                break
            online_pass_interval.append(chunk)
            start += split_per_pass
        return online_pass_interval

    # -- pslib Downpour-table publishing: documented non-ports ----------
    def _pslib_only(self, name):
        raise NotImplementedError(
            f"FleetUtil.{name} publishes pslib pserver table shards, "
            "which do not exist on TPU — checkpoints are whole-state "
            "saves; use io/checkpoint.py (Checkpointer) or "
            "fleet.save_persistables. See MIGRATION.md.")

    def load_fleet_model(self, *a, **k):
        self._pslib_only("load_fleet_model")

    def load_fleet_model_one_table(self, *a, **k):
        self._pslib_only("load_fleet_model_one_table")

    def save_fleet_model(self, *a, **k):
        self._pslib_only("save_fleet_model")

    def write_model_donefile(self, *a, **k):
        self._pslib_only("write_model_donefile")

    def write_xbox_donefile(self, *a, **k):
        self._pslib_only("write_xbox_donefile")

    def write_cache_donefile(self, *a, **k):
        self._pslib_only("write_cache_donefile")

    def save_cache_model(self, *a, **k):
        self._pslib_only("save_cache_model")

    def pull_all_dense_params(self, *a, **k):
        self._pslib_only("pull_all_dense_params")
