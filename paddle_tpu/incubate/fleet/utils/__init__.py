from . import fleet_barrier_util  # noqa: F401
from . import fleet_util  # noqa: F401
from . import hdfs  # noqa: F401
from .fleet_util import FleetUtil  # noqa: F401
from .fleet_barrier_util import check_all_trainers_ready  # noqa: F401
