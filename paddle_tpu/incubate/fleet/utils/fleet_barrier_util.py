"""Parity: incubate/fleet/utils/fleet_barrier_util.py:20
(``check_all_trainers_ready``) — an HDFS-file barrier for fleets whose
processes share only a filesystem.

Same contract as the reference: each trainer drops
``ready.{epoch}.{id}.done`` under ready_path and polls until the file
count is a multiple of worker_num. For in-job synchronization prefer
``fleet.barrier_worker()`` (a DCN barrier, no filesystem); this util
exists for cross-job coordination (e.g. data-ready gating).
"""

import os
import time

from ....parallel.fleet import fleet
from .hdfs import HDFSClient

__all__ = ["check_all_trainers_ready"]


def check_all_trainers_ready(ready_path, epoch, poll_seconds=10):
    trainer_num = fleet.worker_num()
    trainer_id = fleet.worker_index()

    client = HDFSClient(os.getenv("HADOOP_HOME", ""), {
        "fs.default.name": os.getenv("FS_NAME", ""),
        "hadoop.job.ugi": os.getenv("FS_UGI", ""),
    })

    node_ready = f"ready.{epoch}.{trainer_id}.done"
    with open(node_ready, "w"):
        pass
    if not client.is_dir(ready_path):
        client.makedirs(ready_path)
    client.upload(ready_path, node_ready, overwrite=True, retry_times=0)

    while True:
        ready_num = len(client.ls(ready_path))
        if ready_num % trainer_num == 0:
            break
        time.sleep(poll_seconds)
