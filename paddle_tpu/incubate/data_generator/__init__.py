"""MultiSlot data-generator authoring API (the producer half of the CTR
data pipeline).

Parity: python/paddle/fluid/incubate/data_generator/__init__.py:21
(``DataGenerator``), :240 (``MultiSlotStringDataGenerator``), :282
(``MultiSlotDataGenerator``). Users subclass one of these, override
``generate_sample`` (and optionally ``generate_batch``), and run the
script as a dataset ``pipe_command`` — it reads raw lines from stdin and
emits the ``ids_num id1 id2 ...`` MultiSlot text that
``csrc/dataset_feed.cc`` (our native MultiSlotDataFeed) parses.

Differences from the reference (deliberate, API-compatible):
- ``run_from_stdin``/``run_from_memory`` take optional file objects and
  also accept an iterable of lines, so generators are unit-testable
  without process plumbing; called with no args they behave exactly like
  the reference (stdin -> stdout).
- the proto-info side file the reference threatens to generate ("the
  corresponding protofile will be generated") never was in 1.5 either;
  slot typing is tracked only for validation, as there.
"""

import numbers
import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base class for user ETL scripts feeding fluid-style Datasets.

    Subclasses override ``generate_sample(line)`` to turn one raw input
    line into (an iterator of) samples shaped
    ``[(slot_name, [feasign, ...]), ...]``, and may override
    ``generate_batch(samples)`` for whole-batch post-processing
    (ref :21-238).
    """

    def __init__(self):
        self._proto_info = None
        self._line_limit = None
        self.batch_size_ = 32

    def _set_line_limit(self, line_limit):
        """Cap how many input lines run_from_stdin consumes."""
        if not isinstance(line_limit, int):
            raise ValueError(f"line_limit {type(line_limit)} must be int")
        if line_limit < 1:
            raise ValueError("line_limit can not be less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        """Batch size used to group samples before ``generate_batch``."""
        self.batch_size_ = batch_size

    def run_from_memory(self, out=None):
        """Emit samples produced by ``generate_sample(None)`` (debug /
        benchmarking path, ref :67-97)."""
        out = out if out is not None else sys.stdout
        self._drain(self.generate_sample(None)(), out)

    def run_from_stdin(self, lines=None, out=None):
        """Read raw lines, parse each via ``generate_sample``, write
        MultiSlot text (ref :100-139). ``lines``/``out`` default to
        stdin/stdout so a subclass script works as a ``pipe_command``
        unchanged. Honors ``_set_line_limit``."""
        lines = lines if lines is not None else sys.stdin
        out = out if out is not None else sys.stdout

        def samples():
            for n, line in enumerate(lines):
                if self._line_limit is not None and n >= self._line_limit:
                    return
                yield from self.generate_sample(line)()

        self._drain(samples(), out)

    def _drain(self, samples, out):
        batch = []
        for sample in samples:
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._flush(batch, out)
                batch = []
        if batch:
            self._flush(batch, out)

    def _flush(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def generate_sample(self, line):
        raise NotImplementedError(
            "override generate_sample to return an iterator factory over "
            "[(name, [feasign, ...]), ...] samples")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter


def _check_sample(line):
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of generate_sample must be list or tuple, e.g. "
            "[('words', [1926, 8, 17]), ('label', [1])]; got "
            f"{type(line)}")


class MultiSlotStringDataGenerator(DataGenerator):
    """Slots of pre-stringified feasigns -> ``len e1 e2 ...`` text
    (ref :240-280)."""

    def _gen_str(self, line):
        _check_sample(line)
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Typed (int/float) slots -> ``len e1 e2 ...`` text, with slot
    name/order/type consistency validated across lines the way the
    reference tracks _proto_info (ref :282-375: first sample fixes the
    slot set; floats promote a slot to float; empty slots are an error
    the user must pad away)."""

    def _gen_str(self, line):
        _check_sample(line)
        first = self._proto_info is None
        if first:
            # build into a local and commit only on success, so a bad
            # first sample doesn't leave a half-registered slot set
            proto = []
        elif len(line) != len(self._proto_info):
            raise ValueError(
                "the complete field set of two given lines are "
                "inconsistent.")
        proto = proto if first else self._proto_info
        parts = []
        for index, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be str")
            if not isinstance(elements, list):
                raise ValueError(f"elements {type(elements)} must be list")
            if not elements:
                raise ValueError(
                    "the elements of each field can not be empty, you "
                    "need to pad it in generate_sample().")
            if first:
                proto.append((name, "uint64"))
            elif name != proto[index][0]:
                raise ValueError(
                    f"field name mismatch: require "
                    f"<{proto[index][0]}>, got <{name}>.")
            parts.append(str(len(elements)))
            for elem in elements:
                # bool is an int subclass but str(True) would corrupt the
                # MultiSlot text; numpy scalars are fine once coerced
                if isinstance(elem, bool):
                    raise ValueError(
                        "element type bool is ambiguous; cast to int")
                if isinstance(elem, numbers.Integral):
                    elem = int(elem)
                elif isinstance(elem, numbers.Real):
                    proto[index] = (name, "float")
                    elem = float(elem)
                else:
                    raise ValueError(
                        f"element type {type(elem)} must be int or float")
                parts.append(str(elem))
        if first:
            self._proto_info = proto
        return " ".join(parts) + "\n"
