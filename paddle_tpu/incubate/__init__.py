"""incubate — experimental user-facing APIs.

Parity: python/paddle/fluid/incubate/ (fleet lives under
paddle_tpu.parallel.fleet; data_generator here).
"""

from . import data_generator  # noqa: F401
from . import fleet  # noqa: F401
