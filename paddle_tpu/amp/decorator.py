"""AMP optimizer decorator with dynamic loss scaling.

Parity: python/paddle/fluid/contrib/mixed_precision/decorator.py
(decorate, OptimizerWithMixedPrecision) and fp16_lists.py.
"""

import numpy as np

from ..core.framework import default_main_program
from ..core.layer_helper import LayerHelper
from .. import initializer as init_mod
from ..core import unique_name


class AutoMixedPrecisionLists:
    """Parity: fp16_lists.AutoMixedPrecisionLists — ops safe in low precision
    (white), ops kept fp32 (black)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = {"matmul", "mul", "conv2d", "conv3d",
                           "depthwise_conv2d", "conv2d_transpose"}
        self.black_list = {"softmax_with_cross_entropy", "cross_entropy",
                           "mean", "reduce_mean", "layer_norm", "batch_norm",
                           "exp", "log", "softmax"}
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)


CustomOpLists = AutoMixedPrecisionLists


class OptimizerWithMixedPrecision:
    """Wraps an optimizer: scales the loss, unscales grads, skips the update
    on inf/nan, and adapts the loss scale — all inside the jitted step
    (branch-free selects, no host sync), matching the reference's
    update_loss_scaling op pipeline."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8):
        self._optimizer = optimizer
        self._amp_lists = amp_lists
        self._init_loss_scaling = init_loss_scaling
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def rollback_hook(self, factor=None):
        """Recovery hook for robustness.RecoveryPolicy(on_rollback=...):
        after a NonFiniteError rollback, multiply the live loss-scaling
        scope var by `factor` (default: this optimizer's decr_ratio).
        The in-step dynamic update already decays the scale on overflow
        steps, but a rollback RESTORES the pre-fault scale from the
        checkpoint — without this hook the retry replays at exactly the
        scale that just overflowed."""
        factor = self._decr_ratio if factor is None else float(factor)

        def hook(scope, fault):
            import numpy as _np
            import jax.numpy as _jnp
            if self._loss_scaling is None:
                return
            val = scope.get(self._loss_scaling.name)
            if val is not None:
                scope.set(self._loss_scaling.name,
                          _jnp.asarray(_np.asarray(val) * factor))
        return hook

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .policy import cast_model_to_bf16
        helper = LayerHelper("amp")
        program = loss.block.program
        cast_model_to_bf16(program, self._amp_lists)
        block = program.global_block()
        scale_var = helper.create_global_variable(
            persistable=True, name=unique_name.generate("loss_scaling"),
            shape=(), dtype="float32")
        scale_var.stop_gradient = True
        init_mod.ConstantInitializer(self._init_loss_scaling)(scale_var)
        self._loss_scaling = scale_var
        scaled_loss = helper.create_variable_for_type_inference(
            loss.dtype, loss.shape)
        block.append_op("elementwise_mul", {"X": loss, "Y": scale_var},
                        {"Out": scaled_loss}, {"axis": -1})
        params_grads = self._optimizer.backward(
            scaled_loss, startup_program, parameter_list, no_grad_set)
        # unscale grads + detect inf/nan
        finite_flags = []
        for p, g in params_grads:
            block.append_op("elementwise_div", {"X": g, "Y": scale_var},
                            {"Out": g}, {"axis": -1})
            fin = helper.create_variable_for_type_inference("bool", ())
            block.append_op("isfinite", {"X": g}, {"Out": fin})
            finf = helper.create_variable_for_type_inference("float32", ())
            block.append_op("cast", {"X": fin}, {"Out": finf},
                            {"out_dtype": "float32"})
            finite_flags.append(finf)
        all_fin = helper.create_variable_for_type_inference("float32", ())
        block.append_op("sum", {"X": finite_flags}, {"Out": all_fin})
        is_finite = helper.create_variable_for_type_inference("float32", ())
        # is_finite = 1.0 iff every flag is 1
        block.append_op("scale", {"X": all_fin}, {"Out": is_finite},
                        {"scale": 1.0 / max(len(finite_flags), 1)})
        floor_fin = helper.create_variable_for_type_inference("float32", ())
        block.append_op("floor", {"X": is_finite}, {"Out": floor_fin})
        # zero grads when any inf/nan (skip update), else keep
        for p, g in params_grads:
            block.append_op("elementwise_mul", {"X": g, "Y": floor_fin},
                            {"Out": g}, {"axis": -1})
        if self._use_dynamic:
            self._append_loss_scaling_update(helper, block, scale_var, floor_fin)
        optimize_ops = self._optimizer.apply_optimize(
            scaled_loss, startup_program, params_grads)
        return optimize_ops, params_grads

    def _append_loss_scaling_update(self, helper, block, scale_var, finite):
        good = helper.create_global_variable(
            persistable=True, name=unique_name.generate("good_steps"),
            shape=(), dtype="float32")
        good.stop_gradient = True
        init_mod.ConstantInitializer(0.0)(good)
        # good' = (good + 1) * finite   (reset on overflow)
        g1 = helper.create_variable_for_type_inference("float32", ())
        block.append_op("scale", {"X": good}, {"Out": g1},
                        {"scale": 1.0, "bias": 1.0})
        block.append_op("elementwise_mul", {"X": g1, "Y": finite},
                        {"Out": good}, {"axis": -1})
        # hit = 1 when good >= incr_every_n_steps
        import_thresh = helper.create_variable_for_type_inference("float32", ())
        block.append_op("fill_constant", {}, {"Out": import_thresh},
                        {"shape": [], "dtype": "float32",
                         "value": float(self._incr_every_n_steps)})
        hit_b = helper.create_variable_for_type_inference("bool", ())
        block.append_op("greater_equal", {"X": good, "Y": import_thresh},
                        {"Out": hit_b})
        hit = helper.create_variable_for_type_inference("float32", ())
        block.append_op("cast", {"X": hit_b}, {"Out": hit},
                        {"out_dtype": "float32"})
        # scale' = scale * incr^hit * (finite ? 1 : decr)
        incr = helper.create_variable_for_type_inference("float32", ())
        block.append_op("fill_constant", {}, {"Out": incr},
                        {"shape": [], "dtype": "float32",
                         "value": self._incr_ratio})
        incr_f = helper.create_variable_for_type_inference("float32", ())
        block.append_op("elementwise_pow", {"X": incr, "Y": hit},
                        {"Out": incr_f}, {"axis": -1})
        block.append_op("elementwise_mul", {"X": scale_var, "Y": incr_f},
                        {"Out": scale_var}, {"axis": -1})
        # overflow decay: scale *= decr when !finite
        inv = helper.create_variable_for_type_inference("float32", ())
        block.append_op("scale", {"X": finite}, {"Out": inv},
                        {"scale": -1.0, "bias": 1.0})
        decr_amt = helper.create_variable_for_type_inference("float32", ())
        block.append_op("scale", {"X": inv}, {"Out": decr_amt},
                        {"scale": self._decr_ratio - 1.0, "bias": 1.0})
        block.append_op("elementwise_mul", {"X": scale_var, "Y": decr_amt},
                        {"Out": scale_var}, {"axis": -1})
        # reset good on hit
        keep = helper.create_variable_for_type_inference("float32", ())
        block.append_op("scale", {"X": hit}, {"Out": keep},
                        {"scale": -1.0, "bias": 1.0})
        block.append_op("elementwise_mul", {"X": good, "Y": keep},
                        {"Out": good}, {"axis": -1})


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False):
    """Parity: fluid.contrib.mixed_precision.decorate — defaults match
    the 1.5 reference (decorator.py:205): STATIC loss scale 1.0 unless
    use_dynamic_loss_scaling is opted in, decr_ratio 0.8."""
    if amp_lists is None:
        amp_lists = AutoMixedPrecisionLists()
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio)
