"""Automatic mixed precision.

Parity: python/paddle/fluid/contrib/mixed_precision/ (decorate, fp16 lists,
dynamic loss scaling). TPU-first: the native mode is **bf16** (no loss
scaling needed — bf16 shares fp32's exponent range); the fp16-style dynamic
loss scaling API is provided for parity and for fp16-on-TPU experiments.
"""

from .decorator import decorate, CustomOpLists, AutoMixedPrecisionLists
from .policy import cast_model_to_bf16, bf16_guard, get_compute_dtype
