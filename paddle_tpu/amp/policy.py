"""bf16 compute policy.

The MXU consumes bf16 natively; params stay fp32, matmul/conv inputs are
cast to bf16 and accumulate in fp32 (preferred_element_type). This module
holds the global compute-dtype switch the op kernels consult.
"""

import contextlib

_compute_dtype = "float32"


def get_compute_dtype():
    return _compute_dtype


def set_compute_dtype(dtype):
    global _compute_dtype
    _compute_dtype = dtype


@contextlib.contextmanager
def bf16_guard():
    old = _compute_dtype
    set_compute_dtype("bfloat16")
    try:
        yield
    finally:
        set_compute_dtype(old)


def cast_model_to_bf16(program, amp_lists=None):
    """Flip matmul-path op inputs to bf16 by tagging ops; the executor's op
    context applies the cast at trace time (white-list ops only)."""
    from .decorator import AutoMixedPrecisionLists
    lists = amp_lists or AutoMixedPrecisionLists()
    for block in program.blocks:
        for op in block.ops:
            if op.type in lists.white_list:
                op.attrs["__amp_dtype__"] = "bfloat16"
    return program
