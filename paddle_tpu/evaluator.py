"""Deprecated Evaluator shims — state lives host-side, math in metrics.

Parity: `python/paddle/fluid/evaluator.py:45` (Evaluator base,
ChunkEvaluator:127, EditDistance:218, DetectionMAP:299). The reference
deprecates these in favor of fluid.metrics; here each evaluator wraps the
corresponding `paddle_tpu.metrics` class, so the accumulation state is a
host-side metric object rather than scope variables. TPU-native rationale:
evaluator state updated per-batch on host costs nothing on the XLA step
path, and the metric math already has numeric tests in metrics.py.

`reset(executor)` / `eval(executor)` keep the reference call signatures;
the executor argument is accepted and unused (state is host-side).
"""

import warnings

from . import metrics as _metrics

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """Warn-and-delegate base (ref evaluator.py:45)."""

    def __init__(self, name=None, **kwargs):
        warnings.warn(
            "%s is deprecated, please use paddle_tpu.metrics instead." %
            self.__class__.__name__, Warning)
        self.metric = None
        self.states = []
        self.helper_name = name

    def reset(self, executor=None, reset_program=None):
        self.metric.reset()

    def eval(self, executor=None, eval_program=None):
        return self.metric.eval()

    def update(self, *args, **kwargs):
        self.metric.update(*args, **kwargs)


class ChunkEvaluator(Evaluator):
    """Chunk F1 over (num_infer, num_label, num_correct) batch counts."""

    def __init__(self, input=None, label=None, chunk_scheme=None,
                 num_chunk_types=None, excluded_chunk_types=None, name=None):
        super().__init__(name=name)
        self.metric = _metrics.ChunkEvaluator(name=name)


class EditDistance(Evaluator):
    """Average edit distance + error-free sequence rate."""

    def __init__(self, input=None, label=None, ignored_tokens=None,
                 name=None):
        super().__init__(name=name)
        self.metric = _metrics.EditDistance(name=name)


class DetectionMAP(Evaluator):
    """Mean average precision over detection batches."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral", name=None):
        super().__init__(name=name)
        self.metric = _metrics.DetectionMAP(
            name=name, overlap_threshold=overlap_threshold)

    def get_map_var(self):
        return None
