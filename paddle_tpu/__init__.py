"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid 1.5 (reference: /root/reference), built on JAX/XLA/Pallas.

The top-level module doubles as the `fluid` namespace: `import paddle_tpu as
fluid` makes reference recipes (layers/executor/optimizer/io) run unchanged —
but everything underneath is a ground-up TPU design (see SURVEY.md §1):
whole-program XLA compilation, jax.grad autodiff, SPMD parallelism over
jax.sharding meshes, Pallas kernels for the hot paths.
"""

from . import jax_compat    # noqa: F401  must run before any jax-using module
from . import observability
from . import initializer
from .core import (framework, unique_name)
from .core.framework import (Program, Variable, Parameter, program_guard,
                             name_scope, default_main_program,
                             default_startup_program, in_dygraph_mode)
from .core.place import (cuda_pinned_places,
                         CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace,
                         cpu_places, cuda_places, tpu_places,
                         is_compiled_with_cuda, is_compiled_with_tpu)
from .core.executor import (Executor, FetchHandle, Scope, global_scope,
                            scope_guard)
from .core.bucketing import FeedBucketer
from .core.lod import (LoDTensor, create_lod_tensor,
                       create_random_int_lodtensor)
from .core.backward import append_backward, gradients
from .core.param_attr import ParamAttr, WeightNormParamAttr
from .core.data_feeder import DataFeeder
from .core.compiler import (CompiledProgram, ParallelExecutor, BuildStrategy,
                            ExecutionStrategy)
from . import layers
from . import nets
from .layers.io import data  # fluid.data-style (but with batch dim implicit off)
from . import optimizer
from .optimizer import clip
from .optimizer import regularizer
from . import metrics
from . import average
from . import evaluator
from . import net_drawer
from . import contrib
from . import incubate
from . import communicator
from .communicator import Communicator
from . import io
from .io.state import (save_params, save_persistables, save_vars, load_params,
                       load_persistables, load_vars)
from .io.inference_io import save_inference_model, load_inference_model
from .io.dataset import (DatasetFactory, InMemoryDataset, QueueDataset,
                         FileInstantDataset, BoxPSDataset, DataFeedDesc)
from . import dataset
from . import reader
from . import dygraph
from . import parallel
# fluid exposes the transpiler surface at top level (ref fluid/__init__.py
# pulling transpiler.__all__); same names, mesh-first implementations
from . import transpiler
from .transpiler import (DistributeTranspiler,
                         DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from . import profiler
from . import amp
from . import robustness
from . import models
from . import utils
from .utils import install_check   # fluid.install_check.run_check() parity
from . import inference

# fluid-compat: `fluid.data` in 2.x has no implicit batch dim. Keep both:
data = layers.io.fluid_data


def embedding(*args, **kwargs):
    return layers.embedding(*args, **kwargs)


def one_hot(*args, **kwargs):
    return layers.one_hot(*args, **kwargs)


__version__ = "0.1.0"
