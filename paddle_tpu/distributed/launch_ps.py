"""Parameter-server launcher — documented NON-PORT.

Parity target: python/paddle/distributed/launch_ps.py, which spawns
pserver + trainer process groups for the async/geo-SGD parameter-server
mode. TPU pods have no parameter servers: optimizer state shards across
devices (ZeRO-1/fsdp — see parallel/transpiler.py for the documented
re-expression of DistributeTranspiler), and all communication rides XLA
collectives over ICI/DCN. Launch data-parallel workers with
`python -m paddle_tpu.distributed.launch` instead; MIGRATION.md covers
converting pserver configs.
"""


def launch(argv=None):
    raise RuntimeError(
        "paddle_tpu has no parameter-server mode: TPU training shards "
        "optimizer state over devices (ZeRO/fsdp) instead of hosting it "
        "on pservers. Use `python -m paddle_tpu.distributed.launch` with "
        "fleet's DistributedStrategy (see parallel/transpiler.py and "
        "MIGRATION.md).")


if __name__ == "__main__":
    launch()
