"""Multi-process launch utilities.

Parity: python/paddle/distributed/ (launch.py, launch_ps.py). The
launcher sets the PaddleCloud env contract consumed by
`parallel.fleet.PaddleCloudRoleMaker` / `fleet.init`, which bootstraps
`jax.distributed` — the TPU-native replacement for the reference's
NCCL rendezvous over trainer endpoints.
"""


def __getattr__(name):
    # lazy: `python -m paddle_tpu.distributed.launch` re-executes the
    # module, and an eager import here would trigger runpy's
    # found-in-sys.modules warning
    if name in ("launch", "launch_ps", "downpour"):
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
