"""Multi-process training launcher.

Parity: python/paddle/distributed/launch.py (start_procs:132,
launch:243). Spawns one training process per local device/rank with the
PaddleCloud env contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT); inside the script,
`fleet.init()` reads those vars and bootstraps the jax.distributed
cluster (coordinator = endpoint 0), then builds the DCN-aware hybrid
mesh. The reference instead passes the endpoints to a NCCL-id
broadcast; same contract, TPU rendezvous.

Usage (mirrors the reference):

    python -m paddle_tpu.distributed.launch --nproc_per_node=2 \
        examples/distributed_training.py --arg1 ...

Multi-node: give every node the same --cluster_node_ips (comma list)
and its own --node_ip; ranks are node_id * nproc_per_node + local_i.

TPU notes:
- On a real pod each HOST runs ONE process that owns all its local
  chips (jax's one-process-per-host model), so nproc_per_node is
  normally 1 there; >1 is the CPU-mesh/dev workflow where each process
  simulates a host (set JAX_PLATFORMS=cpu +
  xla_force_host_platform_device_count in the training script, as
  examples/distributed_training.py does).
- --selected_gpus is accepted for reference-CLI compatibility and maps
  to per-process PADDLE_SELECTED_DEVICES (scripts may consume it; XLA
  owns real TPU device assignment).
"""

import argparse
import os
import subprocess
import sys
from argparse import REMAINDER


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="paddle_tpu multi-process launcher "
                    "(parity: paddle.distributed.launch)")
    parser.add_argument(
        "--cluster_node_ips", type=str, default="127.0.0.1",
        help="comma-separated IPs of all nodes")
    parser.add_argument("--node_ip", type=str, default="127.0.0.1",
                        help="this node's IP")
    parser.add_argument(
        "--use_paddlecloud", action="store_true",
        help="pick node identity up from the PaddleCloud env "
             "(PADDLE_TRAINERS / POD_IP / PADDLE_TRAINER_ID)")
    parser.add_argument("--started_port", type=int, default=6170,
                        help="first rendezvous port on each node")
    parser.add_argument("--print_config", type=bool, default=True)
    parser.add_argument(
        "--nproc_per_node", type=int, default=None,
        help="processes per node (default: len(--selected_gpus) or 1)")
    parser.add_argument(
        "--selected_gpus", "--selected_devices", dest="selected_gpus",
        type=str, default=None,
        help="reference-compat device list; exported per process as "
             "PADDLE_SELECTED_DEVICES")
    parser.add_argument("--log_dir", type=str, default=None,
                        help="write per-process workerlog.N files here")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=REMAINDER)
    return parser.parse_args(argv)


def start_procs(args):
    """Spawn the local worker processes and wait; raises
    CalledProcessError on the first non-zero exit (reference
    launch.py:132)."""
    node_ips = [x.strip() for x in args.cluster_node_ips.split(",")]
    current_node_ip = args.node_ip
    node_id = node_ips.index(current_node_ip)
    if args.use_paddlecloud:
        # reference launch.py:143: PaddleCloud publishes identity via env
        trainer_nums = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        if trainer_nums != 1:
            current_node_ip = os.environ["POD_IP"]
            node_ips = os.environ["PADDLE_TRAINERS"].split(",")
            node_id = int(os.environ["PADDLE_TRAINER_ID"])

    selected = ([x.strip() for x in args.selected_gpus.split(",")]
                if args.selected_gpus else None)
    nproc = args.nproc_per_node or (len(selected) if selected else 1)
    if selected and nproc > len(selected):
        raise ValueError(
            f"--nproc_per_node={nproc} exceeds the {len(selected)} "
            f"devices in --selected_gpus (a rank per device, no sharing)")
    num_nodes = len(node_ips)
    nranks = num_nodes * nproc

    endpoints = ",".join(f"{ip}:{args.started_port + i}"
                         for ip in node_ips for i in range(nproc))
    if args.print_config:
        print(f"trainers_endpoints: {endpoints} , node_id: {node_id} , "
              f"current_node_ip: {current_node_ip} , num_nodes: "
              f"{num_nodes} , nranks: {nranks}")

    # plain dicts: copy.copy(os.environ) ALIASES the live environment,
    # so mutating it would pollute the launcher's own process
    base_env = dict(os.environ)
    # proxies break the rendezvous sockets (reference drops them too)
    base_env.pop("http_proxy", None)
    base_env.pop("https_proxy", None)

    procs, cmds, log_fns = [], [], []
    for i in range(nproc):
        env = dict(base_env)
        env.update({
            "PADDLE_TRAINER_ID": str(node_id * nproc + i),
            "PADDLE_CURRENT_ENDPOINT":
                f"{current_node_ip}:{args.started_port + i}",
            "PADDLE_TRAINERS_NUM": str(nranks),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_LOCAL_RANK": str(i),
            "PADDLE_NPROC_PER_NODE": str(nproc),
        })
        if selected:
            env["PADDLE_SELECTED_DEVICES"] = selected[i % len(selected)]
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        cmds.append(cmd)
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            fn = open(os.path.join(args.log_dir, f"workerlog.{i}"), "w")
            log_fns.append(fn)
            procs.append(subprocess.Popen(cmd, env=env, stdout=fn,
                                          stderr=fn))
        else:
            procs.append(subprocess.Popen(cmd, env=env))

    # poll ALL ranks: one dead rank leaves peers blocked inside an XLA
    # collective forever, so on first failure terminate the survivors
    # instead of waiting on them in index order
    import time
    first_fail = None
    try:
        while any(p.poll() is None for p in procs):
            for i, p in enumerate(procs):
                if p.poll() is not None and p.returncode != 0:
                    first_fail = (i, p.returncode)
                    break
            if first_fail:
                break
            time.sleep(0.2)
    finally:
        if first_fail:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        for fn in log_fns:
            fn.close()
    if first_fail is None:
        for i, p in enumerate(procs):
            if p.returncode != 0:
                first_fail = (i, p.returncode)
                break
    if first_fail:
        i, rc = first_fail
        raise subprocess.CalledProcessError(returncode=rc, cmd=cmds[i])


def launch(argv=None):
    args = _parse_args(argv)
    start_procs(args)


if __name__ == "__main__":
    launch()
