"""Parity shim: python/paddle/fluid/distributed/downpour.py:24
(``DownpourSGD``) — documented NON-PORT of the Downpour async-SGD
parameter-server trainer (with it, the whole ``fluid.distributed``
package: helper.py MPIHelper/FileSystem, node.py Downpour
Server/Worker protobuf builders, ps_instance.py, ps_pb2.py).

Downpour splits a model into dense params (synced via pserver
push/pull) and sparse embedding tables (sharded over pservers),
trading staleness for CPU-cluster throughput. On a TPU pod the same
scale point is reached synchronously: embeddings shard over the mesh
(GSPMD), optimizer state shards via ZeRO/fsdp
(parallel/transpiler.py), and gradient exchange is compiled ICI
collectives overlapped by XLA's scheduler — no staleness, no separate
server tier, nothing to configure. Use

    from paddle_tpu.incubate.fleet.collective import fleet

with ``DistributedStrategy`` (zero_stage / use_fsdp) instead;
MIGRATION.md maps the Downpour knobs.
"""

__all__ = ["DownpourSGD"]


class DownpourSGD:
    def __init__(self, learning_rate=0.001, window=1):
        raise NotImplementedError(
            "DownpourSGD is a pserver async-SGD trainer with no TPU "
            "analog: shard embeddings/optimizer state over the mesh "
            "instead (fleet + DistributedStrategy(zero_stage=1 or "
            "use_fsdp=True)). See distributed/downpour.py and "
            "MIGRATION.md.")
