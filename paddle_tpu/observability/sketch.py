"""Deterministic mergeable quantile sketch (merging t-digest).

The serving SLO surface needs p50/p90/p99 of TTFT/ITL/e2e over windows
of potentially millions of requests. A histogram with fixed buckets
(metrics.py) answers "how many landed between 25 and 50 ms", not "what
is p99 right now" — the quantile has to be interpolated across decade-
wide buckets and the error is whatever the bucket layout says it is.
This module is the streaming alternative: Dunning's *merging t-digest*
(PAPERS-adjacent standard practice; no external deps), which keeps a
bounded set of weighted centroids whose width shrinks near the tails,
so extreme quantiles — the ones SLOs are written against — are the most
accurate.

Properties the tests pin (tests/api/test_quantile_sketch.py):

- **deterministic**: no randomness anywhere; the same insertion order
  always produces the identical centroid set (and serialized form);
- **mergeable**: `merge()` combines sketches from different windows /
  slots / processes; estimates agree within the rank-error bound
  whatever the merge grouping (associativity up to the bound — exact
  bitwise associativity is impossible for any bounded-memory summary);
- **bounded rank error**: with compression δ, a quantile estimate's
  rank error is O(1/δ), concentrated toward q=0.5 and ~q(1-q)-shaped,
  so p99/p999 are sharper than the median. The tier-1 tests assert
  ≤ 1.5/δ observed rank error on adversarial streams (sorted, reversed,
  heavy duplicates, bimodal, log-tailed).

The scale function is k1: k(q) = (δ / 2π) · asin(2q − 1).
"""

import bisect
import math

__all__ = ["QuantileSketch", "DEFAULT_COMPRESSION"]

DEFAULT_COMPRESSION = 128


class QuantileSketch:
    """Streaming quantile summary over non-negative-weight float samples.

    add() buffers; the buffer is folded into the centroid list when it
    fills (amortized O(log n) per add). quantile()/rank() compress first
    so estimates always reflect every sample.
    """

    __slots__ = ("compression", "_means", "_weights", "_buf", "_count",
                 "_sum", "_min", "_max", "_buf_limit")

    def __init__(self, compression=DEFAULT_COMPRESSION):
        if compression < 8:
            raise ValueError("compression must be >= 8")
        self.compression = int(compression)
        self._means = []        # compressed centroids, ascending
        self._weights = []
        self._buf = []          # pending (value, weight)
        self._buf_limit = 4 * self.compression
        self._count = 0.0
        self._sum = 0.0
        self._min = None
        self._max = None

    # -- ingest -------------------------------------------------------------
    def add(self, value, weight=1.0):
        v = float(value)
        w = float(weight)
        if not math.isfinite(v):
            raise ValueError(f"non-finite sample {value!r}")
        if w <= 0:
            raise ValueError("weight must be > 0")
        self._buf.append((v, w))
        self._count += w
        self._sum += v * w
        self._min = v if self._min is None else min(self._min, v)
        self._max = v if self._max is None else max(self._max, v)
        if len(self._buf) >= self._buf_limit:
            self._compress()

    def add_unit(self, v):
        """add(v, 1.0) minus validation — the serving per-token hot
        path (SLOTracker.observe_token) calls this thousands of times
        per second with engine-computed finite floats; at that rate the
        float()/isfinite checks in add() are a measurable slice of the
        telemetry overhead budget. The resulting sketch state is
        identical to add(v). Callers must guarantee v is a finite
        float."""
        self._buf.append((v, 1.0))
        self._count += 1.0
        self._sum += v
        if self._min is None:
            self._min = self._max = v
        else:
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
        if len(self._buf) >= self._buf_limit:
            self._compress()

    def extend(self, values):
        for v in values:
            self.add(v)

    def merge(self, other):
        """Fold `other`'s mass into this sketch (other is unchanged).
        Centroids re-cluster under this sketch's compression."""
        if other._count == 0:
            return self
        self._buf.extend(zip(other._means, other._weights))
        self._buf.extend(other._buf)
        self._count += other._count
        self._sum += other._sum
        self._min = other._min if self._min is None \
            else min(self._min, other._min)
        self._max = other._max if self._max is None \
            else max(self._max, other._max)
        self._compress()
        return self

    # -- compression --------------------------------------------------------
    def _k(self, q):
        return self.compression / (2.0 * math.pi) * \
            math.asin(2.0 * min(max(q, 0.0), 1.0) - 1.0)

    def _compress(self):
        if not self._buf and len(self._means) <= self.compression:
            return
        pairs = sorted(self._buf + list(zip(self._means, self._weights)),
                       key=lambda p: p[0])
        self._buf = []
        if not pairs:
            return
        total = sum(w for _v, w in pairs)
        means, weights = [], []
        c_mean, c_w = pairs[0]
        done = 0.0              # weight fully emitted before the cluster
        k_lo = self._k(0.0)
        for v, w in pairs[1:]:
            q_hi = (done + c_w + w) / total
            if self._k(q_hi) - k_lo <= 1.0:
                # weighted mean update keeps the cluster centroid exact
                c_mean += (v - c_mean) * (w / (c_w + w))
                c_w += w
            else:
                means.append(c_mean)
                weights.append(c_w)
                done += c_w
                c_mean, c_w = v, w
                k_lo = self._k(done / total)
        means.append(c_mean)
        weights.append(c_w)
        self._means, self._weights = means, weights

    # -- query --------------------------------------------------------------
    @property
    def count(self):
        return self._count

    @property
    def min(self):
        return self._min

    @property
    def max(self):
        return self._max

    @property
    def mean(self):
        return self._sum / self._count if self._count else None

    def _anchors(self):
        """Piecewise-linear (cumulative_weight, value) anchors: min at 0,
        each centroid at its cumulative midpoint, max at count."""
        pts = [(0.0, self._min)]
        cum = 0.0
        for m, w in zip(self._means, self._weights):
            pts.append((cum + w / 2.0, m))
            cum += w
        pts.append((self._count, self._max))
        return pts

    def quantile(self, q):
        """Estimated value at quantile q in [0, 1]; None when empty."""
        if self._count == 0:
            return None
        self._compress()
        q = min(max(float(q), 0.0), 1.0)
        target = q * self._count
        pts = self._anchors()
        xs = [p[0] for p in pts]
        i = bisect.bisect_right(xs, target)
        if i <= 0:
            return pts[0][1]
        if i >= len(pts):
            return pts[-1][1]
        (x0, v0), (x1, v1) = pts[i - 1], pts[i]
        if x1 <= x0:
            return v1
        t = (target - x0) / (x1 - x0)
        return v0 + t * (v1 - v0)

    def rank(self, x):
        """Estimated fraction of mass <= x; None when empty."""
        if self._count == 0:
            return None
        self._compress()
        x = float(x)
        if x < self._min:
            return 0.0
        if x >= self._max:
            return 1.0
        pts = self._anchors()
        for (x0, v0), (x1, v1) in zip(pts, pts[1:]):
            if v0 <= x <= v1:
                if v1 <= v0:
                    return x1 / self._count
                t = (x - v0) / (v1 - v0)
                return (x0 + t * (x1 - x0)) / self._count
        return 1.0

    def summary(self, quantiles=(0.5, 0.9, 0.99)):
        out = {"count": round(self._count, 6), "min": self._min,
               "max": self._max,
               "avg": round(self.mean, 6) if self._count else None}
        for q in quantiles:
            v = self.quantile(q)
            tag = f"p{q * 100:g}".replace(".", "_")
            out[tag] = round(v, 6) if v is not None else None
        return out

    # -- serialization ------------------------------------------------------
    def to_dict(self):
        self._compress()
        return {"compression": self.compression,
                "count": self._count, "sum": self._sum,
                "min": self._min, "max": self._max,
                "centroids": [[m, w] for m, w in
                              zip(self._means, self._weights)]}

    @classmethod
    def from_dict(cls, d):
        s = cls(d["compression"])
        s._count = float(d["count"])
        s._sum = float(d["sum"])
        s._min = d["min"]
        s._max = d["max"]
        s._means = [float(m) for m, _w in d["centroids"]]
        s._weights = [float(w) for _m, w in d["centroids"]]
        return s

    def __repr__(self):
        return (f"QuantileSketch(compression={self.compression}, "
                f"count={self._count:g}, "
                f"centroids={len(self._means)}+{len(self._buf)}buf)")
