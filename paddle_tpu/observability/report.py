"""Shared fluid-style sorted-key event table formatting.

One implementation of the Calls/Total/Min/Max/Ave/Ratio report consumed
by both paddle_tpu.profiler (live report on stop_profiler) and
tools/trace_report.py (offline report over a trace dump) — the two must
never drift apart in columns or sort semantics.
"""

SORT_KEYS = ("calls", "total", "max", "min", "ave")

__all__ = ["SORT_KEYS", "aggregate_events", "format_event_table"]


def aggregate_events(events_ms):
    """(name, dur_ms) iterable -> insertion-ordered
    {name: [calls, total_ms, max_ms, min_ms]}."""
    agg = {}
    for name, dur_ms in events_ms:
        row = agg.setdefault(name, [0, 0.0, 0.0, float("inf")])
        row[0] += 1
        row[1] += dur_ms
        row[2] = max(row[2], dur_ms)
        row[3] = min(row[3], dur_ms)
    return agg


def format_event_table(agg, sorted_key=None, title="Profiling Report",
                       subtitle=None, limit=50):
    """-> list of report lines. sorted_key None keeps insertion order;
    'calls'/'total'/'max'/'min'/'ave' sort descending (fluid parity)."""
    total = sum(row[1] for row in agg.values()) or 1e-12
    rows = list(agg.items())
    if sorted_key is not None:
        keyfn = {"calls": lambda r: r[1][0],
                 "total": lambda r: r[1][1],
                 "max": lambda r: r[1][2],
                 "min": lambda r: r[1][3],
                 "ave": lambda r: r[1][1] / r[1][0]}[sorted_key]
        rows.sort(key=keyfn, reverse=True)
    lines = [f"------------------------->     {title}     "
             f"<-------------------------"]
    if subtitle:
        lines.append(subtitle)
    lines.append(f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>12}"
                 f"{'Max(ms)':>12}{'Ave(ms)':>12}{'Ratio':>8}")
    for name, (calls, tot, mx, mn) in rows[:limit]:
        lines.append(f"{name[:39]:<40}{calls:>8}{tot:>12.3f}{mn:>12.3f}"
                     f"{mx:>12.3f}{tot / calls:>12.3f}{tot / total:>8.2%}")
    return lines
