"""Request-level serving telemetry: lifecycle traces, SLO digests, and
the fault flight recorder.

PR 1's observability sees the world per-*step*; the serving engine's
unit of work is a *request* that lives across many fused-step
iterations. This module is the request-level layer the engine and
scheduler call into:

- **Lifecycle tracing** — every sampled request gets a retroactively
  emitted span tree in the global TraceRecorder (Perfetto):
  ``request <rid>`` root covering submit→end, with ``queue``,
  ``prefill.chunk`` ×N and ``decode`` children, terminated by a
  ``retire``/``cancel``/``deadline`` instant. Spans ride a dedicated
  track per decode slot (``serving slot <n>``; never-admitted requests
  land on ``serving queue``) and carry engine-iteration correlation
  ids in their args, so a trace row lines up with the
  ``serving.iteration`` engine spans and the flight-recorder entries.
  Sampling knob: ``PADDLE_TPU_TRACE_REQUESTS=off|sampled:<rate>|all``
  (deterministic per-request-id hash, no RNG).

- **SLO digests** — TTFT / ITL / e2e / queue-wait land in mergeable
  quantile sketches (sketch.py) twice: a cumulative digest and a
  rolling window. Each completed window publishes
  ``serving.slo.quantile_ms{metric=...,q=p50|p90|p99}`` gauges plus
  ``serving.slo.tokens_per_s``; ``GenerationServer.get_stats()["slo"]``
  snapshots all three views and ``check_slo(targets)`` turns them into
  SRE burn rates.

- **Flight recorder** — a bounded ring of the last K engine iterations
  (scheduler decisions, slot occupancy, block-pool watermarks, per-lane
  positions, kernel dispatch verdict). Engine faults (non-finite
  logits, deadline storms) and GuardedTrainer NaN rollbacks dump it as
  one ``flight-<step>.json`` artifact for postmortems
  (docs/observability.md has the schema).

Everything here is host-side bookkeeping — dict appends and float
arithmetic, no jax — and the engine can switch the whole layer off
(``GenerationServer(telemetry=False)``); the telemetry-on overhead is
benched in perf/bench_telemetry.json (acceptance < 5%).
"""

import collections
import itertools
import json
import math
import os
import threading
import time
import warnings

from .metrics import global_registry
from .sketch import QuantileSketch
from .timeseries import SeriesStore
from .tracing import get_recorder

__all__ = ["ServingTelemetry", "SLOTracker", "TenantLedger",
           "FlightRecorder", "trace_request_mode"]


def _help(name):
    from . import _help as pkg_help
    return pkg_help(name)


# ---------------------------------------------------------------------------
# sampling knob
# ---------------------------------------------------------------------------

def trace_request_mode(raw=None):
    """-> (mode, rate) from PADDLE_TPU_TRACE_REQUESTS.

    off | sampled:<rate in (0,1]> | all (default). Request-id sampling
    is deterministic (splitmix-style integer hash), so a replayed
    stream traces the same requests.

    A malformed value raises ONLY when passed explicitly (programmer
    error); a typo in the env var warns and falls back to the default —
    an observability sampling knob must never be fatal to serving."""
    from_env = raw is None
    if from_env:
        raw = os.environ.get("PADDLE_TPU_TRACE_REQUESTS", "all")
    try:
        return _parse_trace_request_mode(raw)
    except ValueError:
        if not from_env:
            raise
        warnings.warn(
            f"ignoring bad PADDLE_TPU_TRACE_REQUESTS={raw!r} "
            f"(expected off | sampled:<rate> | all); tracing all "
            f"requests", RuntimeWarning, stacklevel=2)
        return "all", 1.0


def _parse_trace_request_mode(raw):
    raw = str(raw).strip().lower()
    if raw in ("", "all", "on", "1", "true"):
        return "all", 1.0
    if raw in ("off", "none", "0", "false"):
        return "off", 0.0
    if raw.startswith("sampled:"):
        try:
            rate = float(raw.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"bad PADDLE_TPU_TRACE_REQUESTS rate in {raw!r}")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"PADDLE_TPU_TRACE_REQUESTS rate must be in [0, 1], "
                f"got {rate}")
        return "sampled", rate
    raise ValueError(
        f"bad PADDLE_TPU_TRACE_REQUESTS {raw!r}: "
        f"expected off | sampled:<rate> | all")


_MASK64 = (1 << 64) - 1


def _rid_hash01(rid):
    """Deterministic rid -> [0, 1) (splitmix64 finalizer)."""
    x = (int(rid) * 0x9E3779B97F4A7C15) & _MASK64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 31
    return x / 2.0 ** 64


def _jsonable(v):
    """Best-effort conversion of flight-entry values to JSON-safe types
    (numpy scalars/arrays arrive from scheduler snapshots)."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, int):
        return v
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return _jsonable(item())
        except Exception:
            pass
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        try:
            return _jsonable(tolist())
        except Exception:
            pass
    return repr(v)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

# Fixed schema of the engine's per-iteration hot-path entry
# (FlightRecorder.record_iteration) and of one lane in lanes_detail
# (ContinuousBatchingScheduler.occupancy_snapshot). The hot path stores
# plain tuples in this field order — CPython untracks tuples of
# scalars, so unlike per-iteration dicts they add no GC pressure next
# to a ~0.25 ms fused step — and entries()/dump() expand them back to
# the documented dict form.
ITER_FIELDS = ("step_ms", "lanes", "emitting", "prefill_tokens",
               "admitted", "retired", "queue_depth", "active_slots",
               "blocks_free", "blocks_in_use", "watermark_blocks",
               "lanes_detail", "kernel", "deadline_cancels")
LANE_FIELDS = ("slot", "rid", "pos", "prefilling", "admit_seq",
               "generated", "first_block", "shared_blocks",
               "cow_copies", "tier", "group", "beam_rank")


def _expand_lanes(lanes):
    """lanes_detail tuples -> the documented list-of-dicts form."""
    if lanes is None:
        return None
    return [dict(zip(LANE_FIELDS, lane)) if isinstance(lane, tuple)
            else lane for lane in lanes]


class FlightRecorder:
    """Bounded ring of per-iteration engine/trainer state, dumped as one
    JSON artifact on a fault.

    Schema (``paddle_tpu.flight/1``)::

        {"schema": "paddle_tpu.flight/1",
         "reason": "non_finite_logits" | "deadline_storm" |
                   "nonfinite_rollback" | ...,
         "step": <step/iteration the fault fired on>,
         "dumped_at_epoch_s": <wall clock>,
         "capacity": K, "recorded": <entries ever recorded>,
         "extra": {...fault detail...},
         "entries": [{"step": ..., "kind": "iteration"|"dispatch"|...,
                      "t_epoch_s": ..., ...recorder-specific fields...},
                     ...]}          # oldest-first, last entry = newest

    The newest entry is annotated with the fault detail before the dump,
    so the LAST element always identifies the offending step."""

    SCHEMA = "paddle_tpu.flight/1"

    def __init__(self, capacity=256, out_dir=None):
        self.capacity = max(1, int(capacity))
        self.out_dir = out_dir if out_dir is not None else \
            os.environ.get("PADDLE_TPU_FLIGHT_DIR", ".")
        self._entries = collections.deque(maxlen=self.capacity)
        self._recorded = 0
        self._lock = threading.Lock()
        self.dump_paths = []
        self._dumps = global_registry().counter(
            "flight.dumps", _help("flight.dumps"))

    def record(self, step, kind="iteration", **fields):
        self.record_fields(step, fields, kind)

    def record_fields(self, step, fields, kind="iteration"):
        """record() without the kwargs repack: `fields` is adopted as
        the entry (mutated in place — pass a dict the caller is done
        with). The engine records an entry every iteration next to a
        ~0.25 ms fused step, where rebuilding an ~18-key dict per call
        is a measurable slice of the <5% telemetry-overhead budget."""
        fields["step"] = int(step)
        fields["kind"] = kind
        fields["t_epoch_s"] = round(time.time(), 6)
        with self._lock:
            self._entries.append(fields)
            self._recorded += 1

    def record_iteration(self, step, values):
        """The engine's per-iteration hot path: `values` is a tuple in
        ITER_FIELDS order (no dicts — a tuple of scalars is untracked
        by the GC, so the ring's constant churn next to a ~0.25 ms
        fused step stops promoting garbage into the older GC
        generations). Expanded back to the documented dict form by
        entries()/dump()/annotate_last()."""
        with self._lock:
            self._entries.append((int(step), round(time.time(), 6),
                                  values))
            self._recorded += 1

    @staticmethod
    def _expand(entry):
        """Ring entry (hot-path tuple OR dict) -> the documented dict
        form, lanes_detail normalized to list-of-dicts."""
        if isinstance(entry, tuple):
            step, t, values = entry
            out = {"step": step, "kind": "iteration", "t_epoch_s": t}
            out.update(zip(ITER_FIELDS, values))
        else:
            out = dict(entry)
        if "lanes_detail" in out:
            out["lanes_detail"] = _expand_lanes(out["lanes_detail"])
        return out

    def annotate_last(self, **fields):
        """Attach fault detail to the newest entry (so the dump's last
        element identifies the offending iteration)."""
        with self._lock:
            if not self._entries:
                return
            last = self._expand(self._entries[-1])
            last.update(fields)
            self._entries[-1] = last

    def entries(self):
        with self._lock:
            return [self._expand(e) for e in self._entries]

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def dump(self, reason, step=None, extra=None, path=None):
        """Write flight-<step>.json into out_dir; returns the path."""
        with self._lock:
            entries = [self._expand(e) for e in self._entries]
            recorded = self._recorded
        if step is None:
            step = entries[-1]["step"] if entries else 0
        if path is None:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, f"flight-{int(step):08d}.json")
            # repeated faults at one step (e.g. GuardedTrainer retries of
            # a deterministic NaN) must not overwrite earlier postmortems
            n = 1
            while os.path.exists(path):
                path = os.path.join(
                    self.out_dir, f"flight-{int(step):08d}-r{n}.json")
                n += 1
        payload = {"schema": self.SCHEMA, "reason": reason,
                   "step": int(step),
                   "dumped_at_epoch_s": round(time.time(), 6),
                   "capacity": self.capacity, "recorded": recorded,
                   "extra": _jsonable(extra or {}),
                   "entries": _jsonable(entries)}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        self.dump_paths.append(path)
        self._dumps.inc()
        return path


# ---------------------------------------------------------------------------
# SLO digests
# ---------------------------------------------------------------------------

SLO_METRICS = ("ttft_ms", "itl_ms", "e2e_ms", "queue_wait_ms")
_QUANTS = ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"))


def _parse_qtag(tag):
    """'p99' -> 0.99, 'p99.9' -> 0.999."""
    t = str(tag).strip().lower()
    if not t.startswith("p"):
        raise ValueError(f"bad quantile tag {tag!r} (want e.g. 'p99')")
    pct = float(t[1:])
    if not 0.0 < pct < 100.0:
        raise ValueError(f"quantile tag {tag!r} out of (0, 100)")
    return pct / 100.0


_TRACKER_SEQ = itertools.count()


class SLOTracker:
    """Cumulative + rolling-window quantile digests for the serving
    latency metrics, published as gauges on each completed window.

    Gauge series carry a per-tracker label (default
    ``{"server": "srv<N>"}``) — two live GenerationServers in one
    process must not clobber each other's window quantiles (the
    Executor's per-instance gauge-label convention). drop_gauges()
    removes this tracker's series from the process-wide registry."""

    #: completed windows retained in snapshot()["recent_windows"]
    RECENT_WINDOWS = 32

    def __init__(self, clock=time.monotonic, window_s=60.0,
                 compression=128, labels=None, recent_windows=None):
        self._clock = clock
        self.window_s = float(window_s)
        self._compression = int(compression)
        self.labels = dict(labels) if labels else \
            {"server": f"srv{next(_TRACKER_SEQ)}"}
        self._published = set()     # label tuples set on _g_quant
        self._lock = threading.Lock()
        self._cum = {m: QuantileSketch(compression) for m in SLO_METRICS}
        self._win = {m: QuantileSketch(compression) for m in SLO_METRICS}
        self._win_start = clock()
        self._started = self._win_start
        self._win_tokens = 0
        self._cum_tokens = 0
        self._last_window = None
        self._last_win_sketches = None      # the previous window's raw
        #                                     digests (window_digest())
        self._recent = collections.deque(
            maxlen=max(1, int(recent_windows if recent_windows
                              is not None else self.RECENT_WINDOWS)))
        self.windows_completed = 0
        reg = global_registry()
        self._g_quant = reg.gauge("serving.slo.quantile_ms",
                                  _help("serving.slo.quantile_ms"))
        self._g_tps = reg.gauge("serving.slo.tokens_per_s",
                                _help("serving.slo.tokens_per_s"))
        self._c_windows = reg.counter("serving.slo.windows",
                                      _help("serving.slo.windows"))

    def observe(self, metric, value_ms):
        with self._lock:
            self._cum[metric].add(value_ms)
            self._win[metric].add(value_ms)

    def count_tokens(self, n=1):
        with self._lock:
            self._win_tokens += n
            self._cum_tokens += n

    def observe_token(self, metric, value_ms):
        """observe(metric) + count_tokens(1) under ONE lock, via the
        sketches' validation-free add_unit — the per-token hot path
        runs this for every generated token next to a ~0.25 ms CPU
        fused step, where each microsecond is ~0.4% of the <5%
        telemetry-overhead budget. value_ms is always an
        engine-computed finite float (add_unit's contract)."""
        v = float(value_ms)
        with self._lock:
            self._cum[metric].add_unit(v)
            self._win[metric].add_unit(v)
            self._win_tokens += 1
            self._cum_tokens += 1

    def digest(self, metric):
        """A COPY of the cumulative sketch for `metric` (mergeable
        across servers/processes via QuantileSketch.merge). A copy, not
        the live object: quantile()/rank() compress in place, and the
        engine worker concurrently observe()s into the original — the
        caller gets a consistent snapshot instead of a race."""
        with self._lock:
            return QuantileSketch.from_dict(self._cum[metric].to_dict())

    def window_digest(self, metric):
        """A COPY of the last-completed-window sketch merged with the
        live window for `metric` — the rolling ~2-window view the
        fleet's burn-rate SERIES is computed from. Unlike the
        cumulative digest (which never forgets a storm), this one
        decays within two window lengths of recovery, so an alert on
        it can actually resolve."""
        with self._lock:
            d = QuantileSketch.from_dict(self._win[metric].to_dict())
            last = self._last_win_sketches
            if last is not None and last[metric].count:
                d.merge(QuantileSketch.from_dict(
                    last[metric].to_dict()))
            return d

    def window_frac_over(self, metric, target):
        """(fraction of rolling-window samples ABOVE target, sample
        count) over the same ~2-window view as window_digest, but
        with NO sketch copies or merges — rank() reads the sketches
        in place. This is the router's per-heartbeat burn-rate feed;
        the copy-and-merge cost of window_digest measured out as a
        multi-percent serving tax at CPU-tiny step times
        (perf/bench_signals.json). Returns (None, 0) when the window
        holds no samples. Cross-sketch combination is exact: rank is
        a fraction of mass, so the union's rank is the count-weighted
        mean of member ranks."""
        with self._lock:
            live = self._win[metric]
            last = (self._last_win_sketches[metric]
                    if self._last_win_sketches is not None else None)
            n = live.count + (last.count if last is not None else 0)
            if not n:
                return None, 0
            over = 0.0
            for sk in (live, last):
                if sk is not None and sk.count:
                    over += (1.0 - sk.rank(float(target))) * sk.count
            return over / n, n

    def maybe_roll(self):
        """Close the window if window_s elapsed; publish its quantile
        gauges. Returns True when a window completed."""
        now = self._clock()
        # lock-free fast path: _win_start is a float read; the worst a
        # racing roll can do is make this read stale and the window
        # close one engine iteration later — this runs every iteration
        if now - self._win_start < self.window_s:
            return False
        with self._lock:
            elapsed = now - self._win_start
            if elapsed < self.window_s:
                return False
            summary = {}
            for m in SLO_METRICS:
                d = self._win[m]
                if d.count:
                    summary[m] = d.summary()
                    for q, tag in _QUANTS:
                        lbl = dict(self.labels, metric=m[:-3], q=tag)
                        self._g_quant.labels(**lbl).set(
                            round(d.quantile(q), 3))
                        self._published.add(tuple(sorted(lbl.items())))
            tps = self._win_tokens / max(elapsed, 1e-9)
            summary["tokens_per_s"] = round(tps, 3)
            summary["elapsed_s"] = round(elapsed, 6)
            summary["tokens"] = self._win_tokens
            summary["closed_at"] = round(now, 6)
            self._g_tps.labels(**self.labels).set(round(tps, 3))
            self._last_window = summary
            self._recent.append(summary)
            # keep the closed window's raw sketches (not just the
            # summary): window_digest() merges them with the live
            # window so windowed burn rates never go blind at a
            # window boundary
            self._last_win_sketches = self._win
            self._win = {m: QuantileSketch(self._compression)
                         for m in SLO_METRICS}
            self._win_tokens = 0
            self._win_start = now
            self.windows_completed += 1
            self._c_windows.inc()
            return True

    def drop_gauges(self):
        """Remove this tracker's gauge series from the process-wide
        registry — a closed server must not report stale window
        quantiles forever (ComponentStats.drop_gauges convention)."""
        with self._lock:
            for lbl in self._published:
                self._g_quant.remove(**dict(lbl))
            self._published.clear()
            self._g_tps.remove(**self.labels)

    def snapshot(self):
        with self._lock:
            now = self._clock()
            cum_elapsed = max(now - self._started, 1e-9)
            return {
                "window_s": self.window_s,
                "windows_completed": self.windows_completed,
                "cumulative": {
                    **{m: self._cum[m].summary() for m in SLO_METRICS
                       if self._cum[m].count},
                    "tokens": self._cum_tokens,
                    "tokens_per_s": round(self._cum_tokens / cum_elapsed,
                                          3),
                },
                "last_window": self._last_window,
                "recent_windows": list(self._recent),
                "current_window": {
                    **{m: self._win[m].summary() for m in SLO_METRICS
                       if self._win[m].count},
                    "tokens": self._win_tokens,
                    "elapsed_s": round(now - self._win_start, 6),
                },
            }

    def check_slo(self, targets):
        """targets: {"ttft_ms": {"p99": 200.0}, "itl_ms": {"p50": 20}}.

        For each (metric, quantile, target) computes, over the
        CUMULATIVE digest: the observed quantile, whether it meets the
        target, the fraction of mass over the target, and the SRE burn
        rate = frac_over / error_budget (budget = 1 - q; burn 1.0 means
        exactly spending the budget, > 1 means burning it down)."""
        checks = []
        ok = True
        for metric, qmap in targets.items():
            if metric not in self._cum:
                raise ValueError(
                    f"unknown SLO metric {metric!r} "
                    f"(know: {SLO_METRICS})")
            # digest() snapshots under the lock: quantile()/rank()
            # compress in place and must not race the worker's observe()
            d = self.digest(metric)
            for tag, target in qmap.items():
                q = _parse_qtag(tag)
                observed = d.quantile(q)
                if observed is None:
                    checks.append({"metric": metric, "quantile": tag,
                                   "target_ms": float(target),
                                   "observed_ms": None, "met": None,
                                   "frac_over": None, "burn_rate": None})
                    continue
                frac_over = 1.0 - d.rank(float(target))
                budget = 1.0 - q
                burn = frac_over / budget if budget > 0 else None
                met = observed <= float(target)
                ok = ok and met
                checks.append({"metric": metric, "quantile": tag,
                               "target_ms": float(target),
                               "observed_ms": round(observed, 3),
                               "met": met,
                               "frac_over": round(frac_over, 6),
                               "burn_rate": round(burn, 4)
                               if burn is not None else None})
        return {"ok": ok, "checks": checks}


# ---------------------------------------------------------------------------
# per-tenant cost attribution
# ---------------------------------------------------------------------------

class TenantLedger:
    """Per-tenant cost vectors + SLO digests, bounded cardinality.

    Requests carry an opaque ``tenant`` identity (``submit(tenant=)``,
    threaded router→engine→scheduler→telemetry); this ledger is where
    the costs they incur are attributed, using data that already
    exists on the request path — prefill/decode tokens, KV
    block-residency in block·iterations (the honest capacity unit:
    blocks held × engine iterations held), queue wait, handoff bytes,
    sheds and failovers. ``get_stats()["tenants"]`` and the
    ``/tenants`` endpoint serve the snapshot, so "which tenant is
    eating the fleet" is a one-scrape question.

    Cardinality is bounded the exporter's way: beyond ``max_tenants``
    distinct identities, new tenants collapse into ``<other>`` (and
    the collapse is counted) — a tenant id is client-supplied input
    and must never grow unbounded label sets. ``None`` attributes to
    ``<anon>``, so un-tenanted traffic is still accounted.

    Latency digests (ttft/e2e per tenant, mergeable QuantileSketch)
    use a smaller default compression than the global SLOTracker:
    there are up to max_tenants × 2 of them per server."""

    ANON = "<anon>"
    OTHER = "<other>"
    #: per-tenant latency digests (per-token metrics stay global —
    #: a per-token per-tenant sketch add would bust the hot path)
    SLO = ("ttft_ms", "e2e_ms")

    def __init__(self, max_tenants=32, compression=64):
        self._max = max(1, int(max_tenants))
        self._compression = int(compression)
        self._lock = threading.Lock()
        self._t = {}                # tenant key -> cost dict
        self._slo = {}              # tenant key -> {metric: sketch}
        self.collapsed = 0
        reg = global_registry()
        self._m_requests = reg.counter(
            "serving.tenant.requests", _help("serving.tenant.requests"))
        self._m_tokens = reg.counter(
            "serving.tenant.generated_tokens",
            _help("serving.tenant.generated_tokens"))
        self._m_blocks = reg.counter(
            "serving.tenant.block_iterations",
            _help("serving.tenant.block_iterations"))
        self._m_sheds = reg.counter(
            "serving.tenant.sheds", _help("serving.tenant.sheds"))

    def _key_locked(self, tenant):
        key = self.ANON if tenant is None else str(tenant)
        if key not in self._t:
            if len(self._t) >= self._max and key != self.OTHER:
                self.collapsed += 1
                return self._key_locked(self.OTHER)
            self._t[key] = {"requests": 0, "prefill_tokens": 0,
                            "decode_tokens": 0, "block_iterations": 0,
                            "queue_wait_ms": 0.0, "handoff_bytes": 0,
                            "sheds": 0, "failovers": 0}
            self._slo[key] = {m: QuantileSketch(self._compression)
                              for m in self.SLO}
        return key

    # -- write side ---------------------------------------------------------
    def finish(self, tenant, prefill_tokens=0, decode_tokens=0,
               block_iterations=0, queue_wait_ms=0.0):
        """One finished request's engine-side cost vector (every
        outcome — a cancelled request's prefill still cost flops)."""
        with self._lock:
            key = self._key_locked(tenant)
            c = self._t[key]
            c["requests"] += 1
            c["prefill_tokens"] += int(prefill_tokens)
            c["decode_tokens"] += int(decode_tokens)
            c["block_iterations"] += int(block_iterations)
            c["queue_wait_ms"] += float(queue_wait_ms)
        self._m_requests.labels(tenant=key).inc()
        self._m_requests.inc()
        if decode_tokens:
            self._m_tokens.labels(tenant=key).inc(int(decode_tokens))
            self._m_tokens.inc(int(decode_tokens))
        if block_iterations:
            self._m_blocks.labels(tenant=key).inc(int(block_iterations))
            self._m_blocks.inc(int(block_iterations))

    def observe(self, tenant, metric, value_ms):
        """One latency sample into the tenant's digest (SLO tuple)."""
        with self._lock:
            key = self._key_locked(tenant)
            self._slo[key][metric].add(float(value_ms))

    def count(self, tenant, kind, n=1):
        """Router-side cost events: sheds / failovers /
        handoff_bytes."""
        with self._lock:
            key = self._key_locked(tenant)
            self._t[key][kind] += n
        if kind == "sheds":
            self._m_sheds.labels(tenant=key).inc(n)
            self._m_sheds.inc(n)

    # -- read side ----------------------------------------------------------
    def digest(self, tenant, metric):
        """Mergeable COPY of one tenant digest (fleet aggregation)."""
        with self._lock:
            key = self.ANON if tenant is None else str(tenant)
            sk = self._slo.get(key)
            if sk is None:
                return QuantileSketch(self._compression)
            return QuantileSketch.from_dict(sk[metric].to_dict())

    def snapshot(self):
        with self._lock:
            tenants = {}
            for key in sorted(self._t):
                entry = dict(self._t[key],
                             queue_wait_ms=round(
                                 self._t[key]["queue_wait_ms"], 3))
                entry["slo"] = {m: self._slo[key][m].summary()
                                for m in self.SLO
                                if self._slo[key][m].count}
                tenants[key] = entry
            return {"max_tenants": self._max,
                    "collapsed": self.collapsed,
                    "tenants": tenants}


def aggregate_tenant_snapshots(snapshots):
    """Sum N TenantLedger.snapshot() payloads into one fleet view:
    scalar costs add; per-tenant SLO summary fields merge
    conservatively (count sums, min takes min, max/avg/quantiles take
    the worst replica — the honest cross-replica read without the raw
    sketches). The router's /tenants endpoint layers its own
    router-side costs (sheds/failovers/handoff_bytes) on top."""
    out = {"max_tenants": 0, "collapsed": 0, "tenants": {}}
    for snap in snapshots:
        if not snap:
            continue
        out["max_tenants"] = max(out["max_tenants"],
                                 snap.get("max_tenants", 0))
        out["collapsed"] += snap.get("collapsed", 0)
        for key, entry in snap.get("tenants", {}).items():
            agg = out["tenants"].setdefault(
                key, {"requests": 0, "prefill_tokens": 0,
                      "decode_tokens": 0, "block_iterations": 0,
                      "queue_wait_ms": 0.0, "handoff_bytes": 0,
                      "sheds": 0, "failovers": 0, "slo": {}})
            for k, v in entry.items():
                if k != "slo":
                    agg[k] = round(agg.get(k, 0) + v, 3)
                    continue
                for m, s in v.items():
                    cur = agg["slo"].setdefault(m, {})
                    for f, fv in s.items():
                        if fv is None:
                            continue
                        old = cur.get(f)
                        if old is None:
                            cur[f] = fv
                        elif f == "count":
                            cur[f] = round(old + fv, 6)
                        elif f == "min":
                            cur[f] = min(old, fv)
                        else:
                            cur[f] = max(old, fv)
    return out


# ---------------------------------------------------------------------------
# per-request lifecycle state
# ---------------------------------------------------------------------------

class _ReqTrace:
    __slots__ = ("rid", "sampled", "submit_perf", "admit_perf",
                 "admit_iteration", "slot", "chunks", "first_token_perf",
                 "first_token_iteration", "last_token_perf", "tokens",
                 "trace_id", "hop", "tenant", "blocks", "queue_wait_ms")

    def __init__(self, rid, sampled, submit_perf):
        self.rid = rid
        self.sampled = sampled
        self.submit_perf = submit_perf
        self.trace_id = None    # fleet trace correlation (router-minted
        self.hop = 0            # TraceContext; None outside a fleet)
        self.tenant = None      # cost-attribution identity
        self.blocks = 0         # KV blocks reserved at admission
        self.queue_wait_ms = 0.0
        self.admit_perf = None
        self.admit_iteration = None
        self.slot = None
        self.chunks = []            # [iteration, ntokens, t_start]
        self.first_token_perf = None
        self.first_token_iteration = None
        self.last_token_perf = None
        self.tokens = 0


class ServingTelemetry:
    """The engine/scheduler-facing facade: lifecycle hooks + SLO
    tracker + flight recorder. All hooks are cheap host bookkeeping and
    safe to call under the scheduler lock; span trees are emitted only
    at request end (and only while a trace capture is live)."""

    def __init__(self, clock=None, window_s=60.0, sample=None,
                 flight_capacity=256, flight_dir=None, deadline_storm=3,
                 compression=128, recorder=None, series_capacity=512,
                 max_tenants=32):
        self.mode, self.sample_rate = trace_request_mode(sample)
        self._clock = clock or time.monotonic
        self.slo = SLOTracker(clock=self._clock,
                              window_s=window_s, compression=compression)
        # the signal plane: per-iteration scalars + SLO window closes
        # land here as (t, value) points on the SAME injected clock;
        # series_capacity=0 switches the store off (the bench's
        # signals-off arm)
        self.series = (SeriesStore(capacity=series_capacity,
                                   label=self.slo.labels.get("server"))
                       if series_capacity else None)
        self.tenants = TenantLedger(max_tenants=max_tenants)
        self.flight = FlightRecorder(capacity=flight_capacity,
                                     out_dir=flight_dir)
        self.deadline_storm = max(1, int(deadline_storm))
        self._rec = recorder or get_recorder()
        self._req = {}
        self._lock = threading.Lock()
        self._iter_deadline_cancels = 0
        self._storm_latched = False
        self._traced_local = 0      # THIS instance's emitted trees (the
        #                             registry counter aggregates
        #                             process-wide across servers)
        reg = global_registry()
        self._m_queue_wait = reg.histogram(
            "serving.queue_wait_ms", _help("serving.queue_wait_ms"))
        self._m_e2e = reg.histogram("serving.e2e_ms",
                                    _help("serving.e2e_ms"))
        self._m_traced = reg.counter("serving.requests_traced",
                                     _help("serving.requests_traced"))
        self._m_faults = reg.counter("serving.faults",
                                     _help("serving.faults"))

    # -- sampling ----------------------------------------------------------
    def sampled(self, rid):
        if self.mode == "all":
            return True
        if self.mode == "off":
            return False
        return _rid_hash01(rid) < self.sample_rate

    def set_recorder(self, recorder):
        """Re-point span-tree emission at a dedicated recorder (the
        fleet router gives every replica its own, so the merged
        Perfetto dump renders per-replica process groups —
        observability/fleet_trace.py)."""
        self._rec = recorder

    # -- request lifecycle hooks (scheduler/engine) ------------------------
    def on_submit(self, rid, ctx=None, tenant=None):
        """`ctx` is a fleet TraceContext: its router-minted sampling
        verdict WINS over this engine's own mode — the decision is
        made once per request so every hop traces or none does (an
        engine re-hashing its replica-local rid, which changes on
        failover, would desync the hops)."""
        sampled = ctx.sampled if ctx is not None else self.sampled(rid)
        st = _ReqTrace(rid, sampled, time.perf_counter())
        if ctx is not None:
            st.trace_id = ctx.trace_id
            st.hop = ctx.hop
        st.tenant = tenant
        with self._lock:
            self._req[rid] = st

    def on_admit(self, rid, slot, iteration, queue_wait_ms, blocks=0):
        self._m_queue_wait.observe(queue_wait_ms)
        self.slo.observe("queue_wait_ms", queue_wait_ms)
        with self._lock:
            st = self._req.get(rid)
            if st is None:
                return
            st.admit_perf = time.perf_counter()
            st.admit_iteration = iteration
            st.slot = slot
            st.blocks = int(blocks)
            st.queue_wait_ms = float(queue_wait_ms)

    def on_prefill_chunk(self, rid, iteration, ntokens):
        # lock-free: dict.get is GIL-atomic and every mutation of an
        # existing _ReqTrace happens on the engine thread (on_submit
        # inserts the rid from the client thread BEFORE it is enqueued,
        # so the engine can never see a half-built entry)
        st = self._req.get(rid)
        if st is None:
            return
        st.chunks.append([iteration, int(ntokens), time.perf_counter()])

    def on_first_token(self, rid, iteration, ttft_ms):
        self.slo.observe_token("ttft_ms", ttft_ms)
        st = self._req.get(rid)     # lock-free: see on_prefill_chunk
        if st is None:
            return
        st.first_token_perf = st.last_token_perf = time.perf_counter()
        st.first_token_iteration = iteration
        st.tokens += 1
        self.tenants.observe(st.tenant, "ttft_ms", ttft_ms)

    def on_token(self, rid, iteration, itl_ms):
        self.slo.observe_token("itl_ms", itl_ms)
        st = self._req.get(rid)     # lock-free: see on_prefill_chunk
        if st is not None:
            st.last_token_perf = time.perf_counter()
            st.tokens += 1

    def on_deadline_cancel(self, rid, iteration):
        # no lock: iteration-scoped counter, engine-thread only (reset
        # in begin_iteration, incremented from _fail during the same
        # thread's plan(), read in end_iteration)
        self._iter_deadline_cancels += 1

    def on_finish(self, rid, iteration, outcome, reason=None, e2e_ms=None,
                  prompt_len=None, generated=None):
        """outcome: 'retire' | 'cancel' | 'deadline'. Emits the span
        tree for sampled requests and drops the per-request state."""
        if outcome == "retire" and e2e_ms is not None:
            self._m_e2e.observe(e2e_ms)
            self.slo.observe("e2e_ms", e2e_ms)
        with self._lock:
            st = self._req.pop(rid, None)
        if st is None:
            return
        # tenant cost attribution happens for EVERY finished request
        # (a cancelled request's prefill still cost flops), regardless
        # of the trace-sampling verdict
        iters = (max(int(iteration) - int(st.admit_iteration) + 1, 1)
                 if st.admit_iteration is not None else 0)
        self.tenants.finish(
            st.tenant,
            prefill_tokens=sum(c[1] for c in st.chunks),
            decode_tokens=st.tokens,
            block_iterations=st.blocks * iters,
            queue_wait_ms=st.queue_wait_ms)
        if outcome == "retire" and e2e_ms is not None:
            self.tenants.observe(st.tenant, "e2e_ms", e2e_ms)
        if not st.sampled or not self._rec.enabled:
            return
        self._emit_tree(st, iteration, outcome, reason, prompt_len,
                        generated)
        self._m_traced.inc()
        with self._lock:
            self._traced_local += 1

    # -- span-tree emission ------------------------------------------------
    def _emit_tree(self, st, end_iteration, outcome, reason, prompt_len,
                   generated):
        rec = self._rec
        end = time.perf_counter()
        track = (f"serving slot {st.slot}" if st.slot is not None
                 else "serving queue")
        # fleet correlation rides EVERY span of the tree: the merged
        # fleet dump is queried by trace_id, and a child span must be
        # attributable without walking back to its root
        base = {"rid": st.rid}
        if st.trace_id is not None:
            base["trace_id"] = st.trace_id
            base["hop"] = st.hop
        root_args = dict(base, outcome=outcome, finish_reason=reason,
                         prompt_len=prompt_len, generated=generated,
                         admit_iteration=st.admit_iteration,
                         end_iteration=end_iteration, slot=st.slot)
        rec.complete(f"request {st.rid}", st.submit_perf, end,
                     cat="serving.request", args=root_args, track=track)
        queue_end = st.admit_perf if st.admit_perf is not None else end
        rec.complete("queue", st.submit_perf, queue_end,
                     cat="serving.request", args=dict(base),
                     track=track)
        # prefill chunks: each closes where the next one opens; the last
        # closes at the first token (or the end, if cut short)
        for i, (it, ntok, t0) in enumerate(st.chunks):
            if i + 1 < len(st.chunks):
                t1 = st.chunks[i + 1][2]
            elif st.first_token_perf is not None:
                t1 = st.first_token_perf
            else:
                t1 = end
            rec.complete("prefill.chunk", t0, t1, cat="serving.request",
                         args=dict(base, iteration=it, tokens=ntok),
                         track=track)
        if st.first_token_perf is not None:
            rec.complete(
                "decode", st.first_token_perf,
                st.last_token_perf or end, cat="serving.request",
                args=dict(base, tokens=st.tokens,
                          first_token_iteration=st.first_token_iteration),
                track=track)
        rec.instant(outcome, cat="serving.request",
                    args=dict(base, iteration=end_iteration),
                    ts=end, track=track)

    # -- engine iteration bracketing --------------------------------------
    def begin_iteration(self, iteration):
        self._iter_deadline_cancels = 0     # engine-thread only

    def end_iteration(self, iteration, values=None, **flight_fields):
        """Record the iteration into the flight ring, roll the SLO
        window, and detect deadline storms. Returns a flight-dump path
        when a storm fired (None otherwise).

        Hot path: `values` is a tuple of the first len(ITER_FIELDS)-1
        fields in ITER_FIELDS order (deadline_cancels is appended
        here) — no per-iteration dicts. Keyword fields are the
        readable fallback for cold callers."""
        cancels = self._iter_deadline_cancels
        if values is not None:
            self.flight.record_iteration(iteration, values + (cancels,))
        else:
            flight_fields["deadline_cancels"] = cancels
            # the **flight_fields kwargs dict is fresh per call; adopt
            # it as the flight entry instead of repacking it
            self.flight.record_fields(iteration, flight_fields)
        rolled = self.slo.maybe_roll()
        if self.series is not None:
            if values is not None:
                step_ms, qd = values[0], values[6]
                slots, in_use = values[7], values[9]
            else:
                step_ms = flight_fields.get("step_ms", 0.0)
                qd = flight_fields.get("queue_depth", 0)
                slots = flight_fields.get("active_slots", 0)
                in_use = flight_fields.get("blocks_in_use", 0)
            self.series.observe_many(
                self._clock(),
                (("engine.step_ms", step_ms),
                 ("engine.queue_depth", qd),
                 ("engine.active_slots", slots),
                 ("engine.blocks_in_use", in_use)))
            if rolled:
                self._series_window_close()
        if cancels >= self.deadline_storm:
            if self._storm_latched:
                return None
            self._storm_latched = True
            return self.fault(iteration, "deadline_storm",
                              {"deadline_cancels": cancels,
                               "threshold": self.deadline_storm})
        self._storm_latched = False
        return None

    def _series_window_close(self):
        """Feed the just-closed SLO window into the series store (the
        recent-windows deque is the source — ISSUE 17 satellite): one
        point per published quantile plus the window throughput, all
        stamped at the window's close time."""
        w = self.slo.snapshot()["recent_windows"][-1]
        t = w["closed_at"]
        pts = [("slo.tokens_per_s", w["tokens_per_s"])]
        for m in SLO_METRICS:
            s = w.get(m)
            if not s:
                continue
            for tag in ("p50", "p90", "p99"):
                if s.get(tag) is not None:
                    pts.append((f"slo.{m}.{tag}", s[tag]))
        self.series.observe_many(t, pts)

    def fault(self, step, kind, detail=None):
        """Mark the newest flight entry with the fault and dump the
        ring. Returns the dump path."""
        self._m_faults.inc()
        self.flight.annotate_last(fault={"kind": kind,
                                         "detail": _jsonable(detail or {})})
        return self.flight.dump(kind, step=step, extra=detail)

    # -- introspection -----------------------------------------------------
    def stats(self):
        out = self.slo.snapshot()
        out["server"] = self.slo.labels.get("server")
        out["trace_requests"] = {
            "mode": self.mode, "rate": self.sample_rate,
            "traced": self._traced_local}
        out["flight"] = {"capacity": self.flight.capacity,
                         "entries": len(self.flight),
                         "dumps": list(self.flight.dump_paths)}
        out["series"] = (None if self.series is None else
                         {"capacity": self.series.capacity,
                          "names": len(self.series.names()),
                          "points": self.series._points_total})
        return out

    def check_slo(self, targets):
        return self.slo.check_slo(targets)

    def close(self):
        """Retire this instance's gauge series (called by the engine's
        close(); counters/histograms aggregate globally and stay)."""
        self.slo.drop_gauges()
