"""Stdlib-only HTTP telemetry endpoint: /metrics, /healthz, /slo,
/memory, /trace, /series, /alerts, /tenants.

Any component can mount one — ``GenerationServer.serve_metrics(port=...)``
and ``Executor.serve_metrics(port=...)`` wrap this; a bare
``serve_metrics()`` exports just the process-wide registry. There is no
dependency beyond ``http.server``: the ROADMAP's fleet story needs a
scrape target on every host, not a metrics SDK.

- ``GET /metrics`` — Prometheus text exposition
  (``MetricsRegistry.to_prometheus()``; label values and HELP text are
  escaped per the format spec).
- ``GET /healthz`` — JSON ``{"status": "ok", ...health_fn()}``; any
  exception from health_fn turns into ``{"status": "error"}`` + HTTP
  500, so a wedged component reads as unhealthy instead of silent.
- ``GET /slo`` — JSON from ``slo_fn()`` (the serving SLO digest
  snapshot), ``{}`` when the component has none.
- ``GET /memory`` — JSON HBM-ledger snapshot (``memory_fn()``; default
  is the process-wide ``compile_insight.hbm_ledger()``): param /
  optimizer-state / PagedKVCache pool bytes and compiled peak-HBM
  estimates per component (docs/observability.md "Compile & memory").
- ``GET /trace`` — JSON bounded ring of recently completed request
  traces (``trace_fn()``; the fleet router mounts its completed-trace
  ring — trace id, hops, lineage, outcome per request; see
  docs/observability.md "Fleet tracing"). Components without a trace
  plane serve an empty ring.
- ``GET /series`` — JSON time-series payload (``series_fn()``; the
  engine mounts its SeriesStore, the fleet router its merged
  fleet+replica view incl. dead replicas' snapshots); empty schema
  shape when the component has no signal plane.
- ``GET /alerts`` — JSON alert lifecycle record (``alerts_fn()``; the
  fleet router mounts its AlertManager — the ROADMAP-5 autoscaler's
  input); empty schema shape otherwise.
- ``GET /tenants`` — JSON per-tenant cost attribution
  (``tenants_fn()``; ``{}`` when the component tracks none). See
  docs/observability.md "Fleet health signals".

Security note: binds 127.0.0.1 by default — the exposition includes
program/shape names and the SLO surface leaks traffic patterns. Bind a
routable host explicitly (``host="0.0.0.0"``) only behind your own
authn/network policy; the server itself adds none (docs/observability.md).
"""

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import _escape, _escape_help, global_registry

__all__ = ["TelemetryServer", "serve_metrics", "FleetRegistryView"]


def _help(name):
    from . import _help as pkg_help
    return pkg_help(name)


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *_a):     # stdout silence: scrapes are periodic
        pass

    def do_GET(self):               # noqa: N802 (http.server contract)
        owner = self.server._owner
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = owner.registry.to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif path == "/healthz":
                payload = {"status": "ok"}
                if owner.health_fn is not None:
                    payload.update(owner.health_fn() or {})
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/slo":
                payload = owner.slo_fn() if owner.slo_fn is not None else {}
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/memory":
                if owner.memory_fn is not None:
                    payload = owner.memory_fn()
                else:
                    from .compile_insight import hbm_ledger
                    payload = hbm_ledger().snapshot()
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/trace":
                # the fleet router's bounded ring of recent completed
                # request traces (observability/fleet_trace.py); a
                # component without a trace plane serves an empty ring
                # so the route is always probeable
                if owner.trace_fn is not None:
                    payload = owner.trace_fn()
                else:
                    # the ONE definition of the schema's empty shape —
                    # FleetTracer.completed_payload() builds on the
                    # same helper, so the two producers of trace_ring/1
                    # cannot diverge
                    from .fleet_trace import empty_trace_ring
                    payload = empty_trace_ring()
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/series":
                # the fleet-health time-series store
                # (observability/timeseries.py); components without a
                # signal plane serve the empty schema shape
                if owner.series_fn is not None:
                    payload = owner.series_fn()
                else:
                    payload = None
                if payload is None:
                    from .timeseries import empty_series
                    payload = empty_series()
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/alerts":
                # the alert rule engine's latched lifecycle record
                # (observability/alerts.py) — the ROADMAP-5
                # autoscaler's input
                if owner.alerts_fn is not None:
                    payload = owner.alerts_fn()
                else:
                    payload = None
                if payload is None:
                    from .alerts import empty_alerts
                    payload = empty_alerts()
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            elif path == "/tenants":
                payload = (owner.tenants_fn()
                           if owner.tenants_fn is not None else {})
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                code = 200
            else:
                body = (json.dumps(
                    {"error": "not found",
                     "endpoints": ["/metrics", "/healthz", "/slo",
                                   "/memory", "/trace", "/series",
                                   "/alerts", "/tenants"]})
                    + "\n").encode()
                ctype = "application/json"
                code = 404
        except Exception as e:      # noqa: BLE001 — a broken stats fn
            # must surface as an unhealthy scrape, never kill the server
            body = (json.dumps({"status": "error", "error": repr(e)})
                    + "\n").encode()
            ctype = "application/json"
            code = 500
        owner._count(path, code)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TelemetryServer:
    """One mounted telemetry endpoint. start() binds and spawns the
    daemon serve thread; close() shuts it down (idempotent)."""

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 slo_fn=None, health_fn=None, memory_fn=None,
                 trace_fn=None, series_fn=None, alerts_fn=None,
                 tenants_fn=None):
        self.registry = registry if registry is not None \
            else global_registry()
        self.slo_fn = slo_fn
        self.health_fn = health_fn
        # None -> the process-wide HBM ledger, resolved per request so
        # a custom memory view stays injectable for tests
        self.memory_fn = memory_fn
        # /trace body (the fleet router's completed-trace ring); None
        # serves an always-probeable empty ring
        self.trace_fn = trace_fn
        # fleet health signals (ISSUE 17): /series time-series store,
        # /alerts rule engine, /tenants cost attribution; None serves
        # the schema's empty shape (series/alerts) or {} (tenants)
        self.series_fn = series_fn
        self.alerts_fn = alerts_fn
        self.tenants_fn = tenants_fn
        self._requested = (host, int(port))
        self._httpd = None
        self._thread = None
        # counts land on the SERVED registry: a custom-registry
        # endpoint's own traffic shows up in its own /metrics output
        # instead of polluting the process-wide registry
        self._requests = self.registry.counter(
            "exporter.requests", _help("exporter.requests"))

    _KNOWN_PATHS = ("/metrics", "/healthz", "/slo", "/memory", "/trace",
                    "/series", "/alerts", "/tenants")

    def _count(self, path, code):
        # unknown paths collapse to one label value: a crawler probing
        # /a1../aN must not mint unbounded series in the global registry
        if path not in self._KNOWN_PATHS:
            path = "<other>"
        self._requests.labels(path=path, code=str(code)).inc()
        self._requests.inc()        # unlabeled aggregate

    def start(self):
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(self._requested, _Handler)
        self._httpd.daemon_threads = True
        self._httpd._owner = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"paddle-tpu-telemetry:{self.port}", daemon=True)
        self._thread.start()
        return self

    @property
    def host(self):
        if self._httpd is not None:
            return self._httpd.server_address[0]
        return self._requested[0]

    @property
    def port(self):
        """The BOUND port (port=0 requests an ephemeral one)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._requested[1]

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    @property
    def closed(self):
        """True once close() ran (or start() never did) — component
        mounts check this to remount instead of returning a dead
        endpoint."""
        return self._httpd is None

    def close(self):
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *_exc):
        self.close()
        return False


class FleetRegistryView:
    """Fleet-aware aggregate /metrics view (ISSUE 11 satellite).

    Counters and histograms aggregate UNLABELED in the process-wide
    registry (the PR 1 convention), so two GenerationServers in one
    process were only separable by mounting one exporter PORT each —
    a fleet of N replicas would need N scrape targets. This view is
    ONE scrape target for the whole fleet: the base registry's
    exposition (process aggregates, exactly as before) with every
    replica's own ``serving.*`` numbers spliced INTO the same metric
    families as additional ``replica="<name>"``-labeled samples. The
    per-replica values come from each replica's ``get_stats()`` (the
    scheduler's per-instance counts — the numbers the global
    aggregate cannot attribute), re-read on every scrape, so a
    dead/closed replica's series vanish instead of going stale.

    Duck-types the registry surface TelemetryServer touches
    (``to_prometheus``/``counter``/``to_dict``); mounted by
    ``FleetRouter.serve_metrics``.
    """

    # stats() key -> exposition family, per kind
    _COUNTERS = (("serving.iterations", "iteration"),
                 ("serving.admitted", "admitted"),
                 ("serving.retired", "retired"),
                 ("serving.cancelled", "cancelled"),
                 ("serving.deadline_cancels", "deadline_cancels"),
                 ("serving.generated_tokens", "generated_tokens"),
                 ("serving.prefill_tokens", "prefill_tokens"))
    _GAUGES = (("serving.queue_depth", "queue_depth"),
               ("serving.active_slots", "active_slots"))
    _PREFIX_COUNTERS = (("serving.prefix.hits", "hits"),
                        ("serving.prefix.misses", "misses"),
                        ("serving.prefix.evictions", "evictions"),
                        ("serving.prefix.cow_copies", "cow_copies"))

    def __init__(self, fleet_fn, base=None):
        self._base = base if base is not None else global_registry()
        # -> [(replica_name, server.get_stats()), ...], re-read per
        # scrape (live replicas only — the router's closure filters)
        self._fleet_fn = fleet_fn

    # -- registry facade (what TelemetryServer/_Handler touch) -------------
    def counter(self, name, help=""):
        return self._base.counter(name, help)

    def gauge(self, name, help=""):
        return self._base.gauge(name, help)

    def get(self, name):
        return self._base.get(name)

    def to_dict(self):
        out = self._base.to_dict()
        out["fleet"] = {name: stats for name, stats in self._fleet_fn()}
        return out

    # -- exposition ---------------------------------------------------------
    def _replica_samples(self, stats):
        """-> (family, kind, value) triples for one replica's stats."""
        for fam, key in self._COUNTERS:
            if key in stats:
                yield fam, "counter", stats[key]
        for fam, key in self._GAUGES:
            if key in stats:
                yield fam, "gauge", stats[key]
        if "blocks_total" in stats and "blocks_free" in stats:
            yield ("serving.blocks_in_use", "gauge",
                   stats["blocks_total"] - stats["blocks_free"])
        pfx = stats.get("prefix")
        if pfx:
            for fam, key in self._PREFIX_COUNTERS:
                if key in pfx:
                    yield fam, "counter", pfx[key]
            if "shared_blocks" in pfx:
                yield ("serving.prefix.shared_blocks", "gauge",
                       pfx["shared_blocks"])

    def _collect(self):
        from . import _help
        extras = {}     # sanitized family -> [kind, help, [lines]]
        for rep, stats in self._fleet_fn():
            for fam, kind, value in self._replica_samples(stats):
                pname = re.sub(r"[^a-zA-Z0-9_:]", "_", fam)
                ent = extras.setdefault(
                    pname, [kind, _escape_help(_help(fam)), []])
                ent[2].append(
                    f'{pname}{{replica="{_escape(rep)}"}} {value}')
        return extras

    def to_prometheus(self):
        """The base exposition with per-replica samples spliced into
        their families (all samples of one family stay contiguous —
        the format's parser contract); families the base never
        recorded are appended with their own HELP/TYPE header."""
        extras = self._collect()
        out, current = [], None

        def _flush(next_family):
            nonlocal current
            if current is not None and current in extras:
                out.extend(extras.pop(current)[2])
            current = next_family

        for line in self._base.to_prometheus().splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                fam = line.split(" ", 3)[2]
                if fam != current:
                    _flush(fam)
            out.append(line)
        _flush(None)
        for pname in sorted(extras):
            kind, help_, lines = extras[pname]
            if help_:
                out.append(f"# HELP {pname} {help_}")
            out.append(f"# TYPE {pname} {kind}")
            out.extend(lines)
        return "\n".join(out) + "\n"


def check_remount(live, port, host):
    """Component-mount guard (GenerationServer/Executor.serve_metrics):
    with a mount already live, an explicit request for a DIFFERENT
    port/host must raise — silently returning the old endpoint leaves
    the asked-for port unbound while the call looks successful.
    ``port=0`` / ``host=None`` mean "whatever is mounted"."""
    want_port = int(port)
    if want_port and want_port != live.port:
        raise ValueError(
            f"telemetry endpoint already mounted on port {live.port}; "
            f"close() it before asking for port {want_port}")
    if host is not None and host != live._requested[0]:
        raise ValueError(
            f"telemetry endpoint already mounted on host "
            f"{live._requested[0]!r}; close() it before asking for "
            f"host {host!r}")


def serve_metrics(port=0, host="127.0.0.1", registry=None, slo_fn=None,
                  health_fn=None, memory_fn=None, trace_fn=None,
                  series_fn=None, alerts_fn=None, tenants_fn=None):
    """Mount and start a telemetry endpoint; returns the running
    TelemetryServer (``.port`` holds the bound port, ``.close()`` stops
    it). Binds loopback by default — see the module security note."""
    return TelemetryServer(registry=registry, host=host, port=port,
                           slo_fn=slo_fn, health_fn=health_fn,
                           memory_fn=memory_fn, trace_fn=trace_fn,
                           series_fn=series_fn, alerts_fn=alerts_fn,
                           tenants_fn=tenants_fn).start()
