"""Process-wide metrics: counters, gauges, ms-bucket histograms.

The reference framework exposes per-op profiler events; the TPU-native
runtime's unit of work is a whole jitted step, so what matters instead is
*where steps spend time* (key build vs trace vs compile vs execute) and
*how the compile caches behave* (hit/miss/evict churn is the difference
between 1ms and 30s steps). This module is the zero-dependency store for
those numbers: thread-safe, label-aware, exportable as JSON (one line,
machine-diffable — perf/ artifacts and tools/trace_report.py read it) and
as Prometheus text exposition (dots sanitized to underscores).

Every metric name the runtime emits is declared in METRIC_SPECS; the
tier-1 lint (tests/api/test_observability.py) fails on an unregistered or
duplicate name, so the namespace stays curated as the system grows.
"""

import contextlib
import json
import re
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "global_registry", "DEFAULT_MS_BUCKETS", "METRIC_SPECS",
]

# Wall-clock millisecond buckets spanning host-dispatch overhead (~0.1ms)
# through big-model XLA compiles (minutes).
DEFAULT_MS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0, 30000.0, 60000.0, 120000.0, float("inf"))


# Canonical metric namespace: (name, kind, help). The instrumentation
# and tools/trace_report.py both key off these names; the lint test
# asserts uniqueness here and that the live registry never strays.
METRIC_SPECS = [
    ("ops.traced", "counter",
     "op dispatches into the jax trace (trace-time, not run-time)"),
    ("executor.steps", "counter", "Executor.run() calls"),
    ("executor.step_ms", "histogram", "wall ms of a whole Executor.run()"),
    ("executor.compiles", "counter",
     "step functions built AND executed for the first time"),
    ("executor.compile_ms", "histogram",
     "first-execution wall ms per (program, shapes): jax trace + XLA "
     "compile + first device run"),
    ("executor.backend_compile_ms", "histogram",
     "XLA backend compile time reported by jax.monitoring, per event"),
    ("executor.span.key_build_ms", "histogram",
     "feed canonicalization + cache-key build + program validation"),
    ("executor.span.trace_ms", "histogram",
     "program -> step-closure construction on a jit-cache miss"),
    ("executor.span.compile_ms", "histogram",
     "first invocation of a fresh step fn (trace+compile+run)"),
    ("executor.span.execute_ms", "histogram",
     "cached step fn invocation"),
    ("executor.span.fetch_ms", "histogram",
     "fetch conversion (device sync + numpy copy)"),
    ("executor.jit_cache.hits", "counter", "step-fn cache hits"),
    ("executor.jit_cache.misses", "counter", "step-fn cache misses"),
    ("executor.jit_cache.evictions", "counter",
     "step-fn cache entries dropped (close()/clear_caches())"),
    ("executor.jit_cache.size", "gauge", "live step-fn cache entries"),
    ("executor.meta_cache.hits", "counter",
     "static (program, feed-keys, fetches) metadata cache hits"),
    ("executor.meta_cache.misses", "counter", "metadata cache misses"),
    ("executor.meta_cache.evictions", "counter",
     "metadata cache entries dropped"),
    ("executor.meta_cache.size", "gauge", "live metadata cache entries"),
    ("executor.uncached_runs", "counter",
     "run() calls with use_program_cache=False (caches bypassed, not "
     "missed)"),
    ("executor.async.dispatches", "counter",
     "run_async() steps dispatched into the in-flight window"),
    ("executor.async.dispatch_ms", "histogram",
     "host wall ms of one run_async dispatch (no device sync)"),
    ("executor.async.inflight", "gauge",
     "async steps dispatched but not yet resolved"),
    ("executor.async.window_waits", "counter",
     "dispatches that found the window full and blocked on the oldest "
     "in-flight step"),
    ("executor.async.host_sync_wait_ms", "histogram",
     "host wall ms blocked on an in-flight step (window admission + "
     "FetchHandle.wait)"),
    ("executor.async.errors", "counter",
     "exceptions captured into FetchHandles (dispatch or device)"),
    ("executor.bucket.batches", "counter",
     "feed dicts padded by a FeedBucketer"),
    ("executor.bucket.pad_waste_elems", "counter",
     "padding elements FeedBucketer added (bucketed minus real size)"),
    ("executor.bucket.shapes", "gauge",
     "distinct post-bucketing feed signatures a FeedBucketer produced"),
    ("executor.fault.guard_steps", "counter",
     "guarded steps whose NaN/Inf sentinel vector was checked"),
    ("executor.fault.nonfinite", "counter",
     "steps on which the NaN/Inf sentinel tripped (NonFiniteError)"),
    ("executor.fault.rollbacks", "counter",
     "GuardedTrainer checkpoint rollbacks after a fault"),
    ("executor.fault.skipped_batches", "counter",
     "offending feeds dropped by RecoveryPolicy(skip_bad_batch=True)"),
    ("executor.fault.preemptions", "counter",
     "preemption requests honored (drain + emergency checkpoint)"),
    ("checkpoint.saves", "counter",
     "checkpoints committed (atomic rename + manifest landed)"),
    ("checkpoint.save_ms", "histogram",
     "wall ms of one committed checkpoint write (payload + manifest)"),
    ("checkpoint.restores", "counter", "checkpoint restores completed"),
    ("checkpoint.restore_ms", "histogram",
     "wall ms of one checkpoint restore (load + CRC validation)"),
    ("checkpoint.write_failures", "counter",
     "checkpoint write attempts that raised (counted before any retry)"),
    ("checkpoint.crc_failures", "counter",
     "manifest/CRC validation failures at load (torn or corrupt file)"),
    ("checkpoint.fallbacks", "counter",
     "restores that skipped a corrupt/incomplete checkpoint and fell "
     "back to an older one"),
    ("checkpoint.evictions", "counter",
     "checkpoints pruned by a CheckpointManager retention policy"),
    ("checkpoint.emergency_saves", "counter",
     "checkpoints written by the preemption drain path"),
    ("checkpoint.retained", "gauge",
     "checkpoints currently retained by a CheckpointManager"),
    ("inference.int8.weights", "counter",
     "weight tensors rewritten to int8 (per-output-channel absmax) by "
     "AnalysisConfig.enable_int8 — the serving-model fold-in "
     "(GPTServingModel.quantize_int8) and the program-path PTQ rewrite "
     "both count here"),
    ("inference.int8.calibrated_activations", "counter",
     "activation tensors given a static quant-dequant scale by the "
     "enable_int8 calibration pass (quant/ptq.calibrate_program over "
     "the predictor's feeds)"),
    ("serving.requests", "counter",
     "generation requests submitted to a GenerationServer"),
    ("serving.admitted", "counter",
     "requests admitted from the queue into a decode slot"),
    ("serving.retired", "counter",
     "requests finished and resolved (eos or length)"),
    ("serving.cancelled", "counter",
     "requests cancelled by the client (queued or mid-stream)"),
    ("serving.deadline_cancels", "counter",
     "requests cancelled because their deadline passed"),
    ("serving.iterations", "counter",
     "scheduler iterations (one fused prefill/decode step each)"),
    ("serving.step_ms", "histogram",
     "wall ms of one serving iteration (plan + fused step + commit)"),
    ("serving.generated_tokens", "counter",
     "tokens emitted across all requests (tokens/s numerator)"),
    ("serving.prefill_tokens", "counter",
     "prompt tokens chunk-prefilled into the paged cache"),
    ("serving.queue_depth", "gauge",
     "requests waiting for a decode slot"),
    ("serving.active_slots", "gauge",
     "decode slots currently owned by a request"),
    ("serving.blocks_in_use", "gauge",
     "KV pool blocks currently allocated (pool utilization numerator)"),
    ("serving.ttft_ms", "histogram",
     "time to first token: submit -> first generated token"),
    ("serving.itl_ms", "histogram",
     "inter-token latency between consecutive generated tokens"),
    ("serving.kernel.traced", "counter",
     "paged_attention dispatches that traced a Pallas ragged paged "
     "attention kernel (one per layer per fused-step trace; unlabeled "
     "aggregate plus a version label: v1, v2)"),
    ("serving.kernel.fallback", "counter",
     "paged_attention dispatches that took the pure-JAX reference path "
     "(unlabeled aggregate plus a reason label — pinned_off, "
     "unsupported, vmap_trace, unsupported_under_shard_map — and a "
     "version=reference label mirroring serving.kernel.traced's)"),
    ("serving.kernel.version", "gauge",
     "kernel generation the LAST paged_attention dispatch took: 1 = "
     "v1 (gather-then-compute, bitwise-stable), 2 = v2 (double-"
     "buffered block streaming + online softmax), 0 = reference "
     "fallback"),
    ("serving.kernel.interpret", "gauge",
     "1 when the paged kernel runs under the Pallas interpreter "
     "(off-TPU), 0 when compiled for a real TPU"),
    ("serving.prefix.hits", "counter",
     "prefix-cache chunk probes that matched an indexed block (token-"
     "verified; the bench's hit-rate numerator)"),
    ("serving.prefix.misses", "counter",
     "prefix-cache chunk probes that missed (absent key, or a hash "
     "collision rejected by the token verify — the chain walk stops "
     "at the first miss, so one admission counts at most one miss)"),
    ("serving.prefix.shared_blocks", "gauge",
     "indexed KV blocks currently referenced by at least one live "
     "request on top of the index's own ref"),
    ("serving.prefix.evictions", "counter",
     "cached blocks evicted from the prefix index (LRU leaf-first, "
     "under watermark pressure or chaos injection)"),
    ("serving.prefix.cow_copies", "counter",
     "copy-on-write block copies (a shared block about to be written "
     "was copied to a fresh block and the table repointed)"),
    ("serving.group.requests", "counter",
     "fork-group submissions admitted (one per RequestGroup: n>1 "
     "parallel sampling or beam search)"),
    ("serving.group.lanes", "counter",
     "lanes admitted on behalf of fork groups (K per group — the "
     "per-lane cousin of serving.group.requests)"),
    ("serving.group.forks", "counter",
     "follower lanes forked off a completed leader prefill (table "
     "aliases of the shared prompt blocks — K-1 per group, zero "
     "block copies)"),
    ("serving.group.cow_copies", "counter",
     "copy-on-write copies taken by fork-group lanes diverging off "
     "shared blocks (prompt boundary or post-reorder suffix)"),
    ("serving.beam.reorders", "counter",
     "beam-search steps whose top-K selection changed parent "
     "hypotheses — each one is a host-side block-TABLE remap, not a "
     "KV move"),
    ("serving.guided.masked_steps", "counter",
     "lane-iterations whose logits carried a guided-decoding "
     "constraint mask (additive -inf rows inside the fused step)"),
    ("serving.guided.violations", "counter",
     "tokens committed with no automaton transition (unreachable "
     "while masks are fed; counted, not raised, under chaos "
     "mask-starve so the serving loop survives)"),
    ("serving.spec.proposed", "counter",
     "draft tokens submitted to fused-step verification (columns "
     "1..q-1 of speculative decode lanes)"),
    ("serving.spec.accepted", "counter",
     "verified draft tokens accepted (greedy: matched the target's "
     "own argmax; rejection mode: passed the acceptance draw)"),
    ("serving.spec.accept_rate", "gauge",
     "process-cumulative accepted/proposed ratio across all "
     "speculative schedulers"),
    ("serving.kv.quant.pool_bytes", "gauge",
     "TRUE footprint of a quantized KV block pool: int8 codes plus the "
     "f32 per-row scale pools, across k+v and every layer (label: "
     "server; absent for dense pools)"),
    ("serving.kv.quant.bytes_saved", "gauge",
     "bytes the int8 KV pool saves vs the same block count dense in "
     "the compute dtype (dense_equiv - int8+scales; label: server)"),
    ("serving.kv.tier.host_blocks", "gauge",
     "host-RAM spill-pool capacity in blocks (the tier the device "
     "pool evicts into and preemption parks in; label: server; "
     "absent without a host tier)"),
    ("serving.kv.tier.spills", "gauge",
     "cumulative device->host block copies: evictions that kept the "
     "prefix KV alive in the host tier plus preempted requests' "
     "parked blocks (label: server)"),
    ("serving.kv.tier.swap_ins", "gauge",
     "cumulative host->device block copies: spilled chains re-adopted "
     "on a prefix hit plus preempted requests resumed (label: server)"),
    ("serving.kv.tier.preempts", "gauge",
     "cumulative decode lanes parked in the host tier under block "
     "pressure, position and stream state intact (label: server)"),
    ("serving.kv.tier.resumes", "gauge",
     "cumulative preempted requests swapped back into device blocks "
     "and continued bitwise (label: server)"),
    ("serving.kv.tier.reprefills_avoided", "gauge",
     "cumulative prefix-chain blocks served by host-tier swap-in "
     "instead of re-running prefill (label: server)"),
    ("serving.mesh.axis_size", "gauge",
     "tensor-parallel mesh axis size a GenerationServer shards its "
     "fused step and KV pools over (label: server; absent single-"
     "device)"),
    ("serving.mesh.shard_pool_bytes", "gauge",
     "KV block-pool bytes ONE device commits under the serving mesh "
     "(pool_bytes/tp — the capacity unit admission watermarks "
     "protect; label: server)"),
    ("serving.mesh.psums_per_step", "gauge",
     "psum collectives one fused serving step pays (2 per layer: "
     "attention o-proj + ffn down-projection; label: server)"),
    ("serving.fleet.routed", "counter",
     "requests routed to a replica by the FleetRouter (unlabeled "
     "aggregate plus a policy label: affinity, least_loaded, prefill, "
     "decode)"),
    ("serving.fleet.sheds", "counter",
     "requests rejected by fleet admission control (AdmissionRejected "
     "raised; unlabeled aggregate plus a scope label: fleet = SLO "
     "burn-rate breach, capacity = no live replica)"),
    ("serving.fleet.failovers", "counter",
     "in-flight requests re-admitted on a surviving replica after "
     "their replica died mid-stream"),
    ("serving.fleet.handoffs", "counter",
     "disaggregated prefill->decode migrations (one per request that "
     "finished chunked prefill on the prefill pool and moved to a "
     "decode replica)"),
    ("serving.fleet.handoff_blocks", "counter",
     "KV pool blocks copied across replica caches by disaggregated "
     "handoffs (blocks the decode replica did NOT have to re-prefill)"),
    ("serving.fleet.replicas", "gauge",
     "live (ok or draining) replicas behind a FleetRouter (label: "
     "router)"),
    ("serving.fleet.replica_load", "gauge",
     "per-replica live load the router balances on: queue_depth + "
     "active_slots (labels: router, replica; series removed when the "
     "replica dies, is evicted by the crash-loop breaker, or the "
     "router closes)"),
    ("serving.fleet.hangs", "counter",
     "replicas declared HUNG by the supervisor watchdog (progress "
     "marks frozen for N heartbeats with work pending) and torn down "
     "so failover re-admits their in-flight requests"),
    ("serving.fleet.resurrections", "counter",
     "dead replicas respawned by the fleet supervisor and returned "
     "to rotation after a half-open probe, prefix cache re-warmed "
     "from the router's chunk-popularity digest"),
    ("serving.fleet.crash_loops", "counter",
     "failed resurrection attempts (spawn/probe failure, or a "
     "resurrected replica dying again before retiring a single "
     "request); max_crash_loops consecutive trips permanently evict "
     "the replica slot"),
    ("serving.fleet.quarantines", "counter",
     "poison requests quarantined by the router: implicated in >= "
     "poison_threshold replica deaths (engine faults naming their "
     "lane), failed with PoisonRequestError instead of re-admitted "
     "onto another survivor"),
    ("serving.fleet.trace.requests", "counter",
     "requests the router minted a SAMPLED fleet trace context for "
     "(one trace id + one sampling verdict per request, obeyed on "
     "every hop across handoff/failover/resurrection)"),
    ("serving.fleet.trace.completed", "counter",
     "finished request traces recorded into the router's bounded "
     "/trace ring (trace id, hops, lineage, outcome)"),
    ("serving.fleet.trace.dumps", "counter",
     "merged fleet Perfetto dumps produced by FleetRouter.dump_trace "
     "(fleet track + per-replica captures incl. death snapshots)"),
    ("serving.fleet.rpc.requests", "counter",
     "RPC calls issued to subprocess replica workers over the "
     "localhost socket transport (submit/step/cancel/handoff/...)"),
    ("serving.fleet.rpc.retries", "counter",
     "RPC attempts retried after a connection-level failure "
     "(reset/refused/truncated frame) with exponential backoff; "
     "exhausting the budget classifies the worker DEAD"),
    ("serving.fleet.rpc.timeouts", "counter",
     "RPC calls that hit their deadline with the connection still "
     "open — never retried (the worker may be mid-step); classifies "
     "the worker HUNG-suspect for the watchdog"),
    ("serving.fleet.autoscale.scale_ups", "counter",
     "replica slots added by the SLO-driven autoscaler (burn rate "
     "above up_threshold for up_samples consecutive signal samples)"),
    ("serving.fleet.autoscale.scale_downs", "counter",
     "replicas drained by the autoscaler (burn rate below "
     "down_threshold for down_samples consecutive samples — "
     "scale-down-slow hysteresis)"),
    ("serving.fleet.autoscale.blocked", "counter",
     "autoscaler scale-ups refused by the safety rail: a crash-loop "
     "breaker entry open, a slot evicted, or a death awaiting "
     "resurrection — a crashing image must never trigger a spawn "
     "storm"),
    ("serving.fleet.autoscale.desired", "gauge",
     "replica count the autoscaler currently wants (label: router); "
     "compare with serving.fleet.replicas to watch convergence"),
    ("tracing.dropped_events", "counter",
     "trace events dropped by the bounded ring buffer (drop-oldest)"),
    ("serving.queue_wait_ms", "histogram",
     "submit -> decode-slot admission wait"),
    ("serving.e2e_ms", "histogram",
     "submit -> retirement end-to-end latency (retired requests only)"),
    ("serving.slo.quantile_ms", "gauge",
     "per-window latency quantiles from the SLO digests (labels: "
     "metric=ttft|itl|e2e|queue_wait, q=p50|p90|p99, server=<per-"
     "tracker id> so concurrent servers never clobber each other)"),
    ("serving.slo.tokens_per_s", "gauge",
     "generated tokens/sec over the last completed SLO window "
     "(label: server)"),
    ("serving.slo.windows", "counter", "completed SLO digest windows"),
    ("serving.series.points", "counter",
     "time-series points recorded (all stores)"),
    ("serving.series.dropped_points", "counter",
     "time-series points evicted by ring wrap (all stores)"),
    ("serving.alerts.fired", "counter",
     "alert rule transitions to firing"),
    ("serving.alerts.resolved", "counter",
     "alert rule transitions firing -> resolved"),
    ("serving.alerts.active", "gauge",
     "alert rules currently firing (label: manager; plus an unlabeled "
     "aggregate)"),
    ("serving.tenant.requests", "counter",
     "finished requests per tenant (label: tenant; bounded "
     "cardinality, overflow collapses to <other>, untagged to <anon>)"),
    ("serving.tenant.generated_tokens", "counter",
     "decode tokens generated per tenant (label: tenant)"),
    ("serving.tenant.block_iterations", "counter",
     "KV block-residency per tenant in block*iterations — blocks "
     "reserved x engine iterations held (label: tenant)"),
    ("serving.tenant.sheds", "counter",
     "router admission sheds per tenant (label: tenant)"),
    ("serving.requests_traced", "counter",
     "requests whose lifecycle span tree was emitted into the trace "
     "recorder (PADDLE_TPU_TRACE_REQUESTS sampling knob)"),
    ("serving.faults", "counter",
     "engine fault events (non-finite logits, deadline storms) that "
     "dumped the flight recorder"),
    ("flight.dumps", "counter",
     "flight-recorder JSON artifacts written (engine faults, "
     "GuardedTrainer NaN rollbacks)"),
    ("exporter.requests", "counter",
     "telemetry HTTP endpoint requests served (labels: path, code; "
     "plus an unlabeled aggregate)"),
    ("executor.recompile.events", "counter",
     "post-warm jit-cache misses (recompiles) with a recorded key diff "
     "(which feed var changed shape/dtype vs the nearest cached "
     "signature)"),
    ("executor.recompile.storms", "counter",
     "recompile-storm warnings raised (>= storm-threshold recompiles "
     "inside the rate window; see docs/observability.md)"),
    ("executor.recompile.window_events", "gauge",
     "recompiles inside the current rate window (labeled per "
     "executor)"),
    ("memory.bytes", "gauge",
     "HBM-ledger bytes per (component, kind): params, optimizer, "
     "kv_cache, other, peak_hbm (docs/observability.md 'Compile & "
     "memory')"),
    ("memory.total_bytes", "gauge",
     "sum of live resident HBM-ledger bytes (params + optimizer + "
     "kv_cache + other; peak_hbm estimates excluded — they overlap "
     "the same buffers)"),
    ("memory.entries", "gauge", "live HBM-ledger entries"),
    ("executor.dp.runs", "counter", "data-parallel (mesh) run() calls"),
    ("executor.dp.shard_state_ms", "histogram",
     "feed/state device placement on the data-parallel path"),
    ("profiler.events", "counter", "profiler.record_event regions"),
]


def _label_key(labels):
    return tuple(sorted(labels.items())) if labels else ()


class _Child:
    """One (metric, label-set) time series."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    def value(self):
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def snapshot(self):
        return {"value": self._value}


class _GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        self.inc(-n)

    def value(self):
        return self._value

    def reset(self):
        self.set(0)

    def snapshot(self):
        return {"value": self._value}


class _HistogramChild(_Child):
    __slots__ = ("_buckets", "_counts", "_count", "_sum", "_min", "_max")

    def __init__(self, buckets=DEFAULT_MS_BUCKETS):
        super().__init__()
        bs = tuple(sorted(buckets))
        if not bs or bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self._buckets = bs
        self._counts = [0] * len(bs)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, v):
        v = float(v)
        with self._lock:
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    break
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    @contextlib.contextmanager
    def time_ms(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe((time.perf_counter() - t0) * 1e3)

    def value(self):
        return self._count

    def reset(self):
        with self._lock:
            self._counts = [0] * len(self._buckets)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def summary(self):
        with self._lock:
            avg = self._sum / self._count if self._count else 0.0
            return {"count": self._count, "sum": round(self._sum, 6),
                    "min": self._min, "max": self._max,
                    "avg": round(avg, 6)}

    def snapshot(self):
        with self._lock:
            cum, buckets = 0, []
            for le, c in zip(self._buckets, self._counts):
                cum += c
                buckets.append([le if le != float("inf") else "+Inf", cum])
        out = self.summary()
        out["buckets"] = buckets
        return out


class _Metric:
    kind = None
    _child_cls = None

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children = {}
        self._default = None    # lazily-created no-label child

    def _make_child(self):
        return self._child_cls()

    def labels(self, **labels):
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
                if not key:
                    self._default = child
        return child

    def remove(self, **labels):
        """Drop one label-set's series (e.g. a closed executor's gauges)."""
        with self._lock:
            self._children.pop(_label_key(labels), None)

    def _base(self):
        d = self._default
        return d if d is not None else self.labels()

    # no-label convenience: metric acts as its own unlabeled child
    def value(self):
        return self._base().value()

    def reset(self):
        with self._lock:
            children = list(self._children.values())
        for c in children:
            c.reset()

    def series(self):
        """[(labels_dict, child), ...] snapshot."""
        with self._lock:
            return [(dict(k), c) for k, c in self._children.items()]

    def snapshot(self):
        return {"name": self.name, "type": self.kind, "help": self.help,
                "values": [dict(labels=lbl, **c.snapshot())
                           for lbl, c in self.series()]}


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n=1):
        self._base().inc(n)


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v):
        self._base().set(v)

    def inc(self, n=1):
        self._base().inc(n)

    def dec(self, n=1):
        self._base().dec(n)


class Histogram(_Metric):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help="", buckets=DEFAULT_MS_BUCKETS):
        super().__init__(name, help)
        self._buckets_spec = buckets

    def _make_child(self):
        return _HistogramChild(self._buckets_spec)

    def observe(self, v):
        self._base().observe(v)

    def time_ms(self):
        return self._base().time_ms()

    def summary(self):
        return self._base().summary()

    def summaries(self):
        return [(lbl, c.summary()) for lbl, c in self.series()]


_NAME_RE = re.compile(r"^[a-z][a-z0-9_.]*$")


class MetricsRegistry:
    """Name -> metric store. Process-wide singleton via global_registry();
    components (each Executor) also keep a private instance so
    get_stats() can answer per-instance questions."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.RLock()

    def _get_or_create(self, name, cls, help, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"bad metric name {name!r}: lowercase dotted identifiers "
                f"only (pattern {_NAME_RE.pattern})")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name, help=""):
        return self._get_or_create(name, Counter, help)

    def gauge(self, name, help=""):
        return self._get_or_create(name, Gauge, help)

    def histogram(self, name, help="", buckets=DEFAULT_MS_BUCKETS):
        return self._get_or_create(name, Histogram, help, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        """Zero every series (keeps registrations)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def clear(self):
        """Drop every metric (tests only)."""
        with self._lock:
            self._metrics.clear()

    # -- export -------------------------------------------------------------
    def to_dict(self):
        with self._lock:
            metrics = list(self._metrics.values())
        return {"metrics": [m.snapshot() for m in
                            sorted(metrics, key=lambda m: m.name)]}

    def to_json(self, indent=None):
        """One-line JSON by default: perf/ artifacts are parsed line-wise
        (tools/bench_watch.py _artifact_ok reads the LAST line)."""
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    def to_prometheus(self):
        """Prometheus text exposition format, 'name.with.dots' sanitized
        to legal underscore form. Label values AND help text are escaped
        per the format spec (labels: backslash/quote/newline; HELP:
        backslash/newline) — a label like shape="(4, 8)" or a help
        string spanning lines must never emit an unscrapeable line."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            pname = re.sub(r"[^a-zA-Z0-9_:]", "_", m.name)
            if m.help:
                lines.append(f"# HELP {pname} {_escape_help(m.help)}")
            lines.append(f"# TYPE {pname} {m.kind}")
            for lbl, child in m.series():
                base_lbl = _fmt_labels(lbl)
                if m.kind in ("counter", "gauge"):
                    lines.append(f"{pname}{base_lbl} {child.value()}")
                else:
                    snap = child.snapshot()
                    for le, cum in snap["buckets"]:
                        le_lbl = _fmt_labels(dict(lbl, le=str(le)))
                        lines.append(f"{pname}_bucket{le_lbl} {cum}")
                    lines.append(f"{pname}_sum{base_lbl} {snap['sum']}")
                    lines.append(f"{pname}_count{base_lbl} {snap['count']}")
        return "\n".join(lines) + "\n"


def _fmt_labels(labels):
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _escape(v):
    """Label-value escaping per the exposition format: backslash,
    double-quote, newline."""
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v):
    """HELP-line escaping per the exposition format: backslash and
    newline only (quotes are legal in help text)."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


_GLOBAL = MetricsRegistry()


def global_registry():
    return _GLOBAL
