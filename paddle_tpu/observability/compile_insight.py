"""Compile-plane observability: cost/memory attribution, the HBM
ledger, and recompile-storm detection.

PR 7 instrumented the *request* plane (what the serving stack does per
request); this module instruments the *compile* plane — what each
compiled Program costs. Fluid 1.5 answered "where did my memory go /
why is this slow" with a memory-optimization pass and an op profiler;
paddle_tpu handed both jobs to XLA and, until now, got nothing back.
Four pieces:

- **XLA extractor** (`extract_xla_cost` / `extract_xla_memory`) — the
  compiler's own numbers for a jitted entry, from
  ``lowered.cost_analysis()`` / ``compiled.memory_analysis()``, with
  graceful ``None`` degradation on backends that don't report. The
  caller falls back to the static analyzer.
- **Static analyzer** (`analyze_jaxpr` / `analyze_program`) —
  backend-independent estimates. `analyze_jaxpr` walks the traced
  jaxpr of the *whole* step (forward + grad + optimizer included, so
  no hand-waved 3x multiplier) counting per-primitive FLOPs and
  intermediate bytes; `analyze_program` walks Block/OpDesc for the
  fluid-level attribution (per-op-type FLOPs via utils/model_stat,
  param vs optimizer-state bytes, activation bytes).
- **HBM ledger** (`HBMLedger` / `hbm_ledger()`) — one process-wide
  account of device-memory commitments: param bytes, optimizer-state
  bytes, serving `PagedKVCache` pool bytes, compiled peak-HBM
  estimates. Components register/retire entries; totals publish as
  ``memory.*`` gauges and the exporter's ``/memory`` endpoint serves
  the snapshot. Resident kinds (params/optimizer/kv_cache/other) sum
  into ``memory.total_bytes``; ``peak_hbm`` entries are derived
  *estimates* over mostly the same buffers and are reported but never
  summed.
- **Recompile-storm detector** (`RecompileTracker`) — every jit-cache
  miss past a warm threshold records a structured *key diff* (which
  feed var changed shape/dtype vs the nearest cached signature),
  emits ``executor.recompile.*`` metrics, and raises a rate-windowed
  `RecompileStormWarning` pointing at `core.bucketing.FeedBucketer`.

`Executor.explain(program, feed)` assembles the full report
(docs/observability.md "Compile & memory"); `tools/compile_report.py`
renders it as a table.
"""

import math
import os
import sys
import threading
import time
import warnings

import numpy as np

from .metrics import global_registry

__all__ = [
    "extract_xla_cost", "extract_xla_memory", "analyze_jaxpr",
    "analyze_program", "explain_entry", "array_nbytes",
    "HBMLedger", "hbm_ledger", "RESIDENT_KINDS", "LEDGER_KINDS",
    "RecompileTracker", "RecompileStormWarning",
]


def _help(name):
    from . import _help as pkg_help
    return pkg_help(name)


def array_nbytes(a):
    """Device/host array byte size (bf16-correct: jax registers
    ml_dtypes, so a.dtype.itemsize is always right)."""
    return int(a.size) * np.dtype(a.dtype).itemsize


def array_nbytes_per_device(a):
    """Bytes ONE device holds: for a mesh-sharded jax Array the shard
    shape, for replicated/host arrays the full size. The HBM ledger's
    unit — per-device HBM is what capacity questions are about."""
    sharding = getattr(a, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shard = sharding.shard_shape(tuple(a.shape))
            return int(math.prod(shard)) * np.dtype(a.dtype).itemsize
        except Exception:
            pass
    return array_nbytes(a)


# ---------------------------------------------------------------------------
# XLA extractor — the compiler's own numbers, None when it won't say
# ---------------------------------------------------------------------------

def extract_xla_cost(lowered=None, compiled=None):
    """XLA cost model for a jitted entry: {"flops", "bytes_accessed",
    "raw"} or None when the backend doesn't report (some builds return
    nothing, raise NotImplementedError, or report flops=-1)."""
    for stage in (compiled, lowered):
        if stage is None:
            continue
        try:
            costs = stage.cost_analysis()
        except Exception:
            continue
        # older jax returns a one-element list of dicts
        if isinstance(costs, (list, tuple)):
            costs = costs[0] if costs else None
        if not costs:
            continue
        flops = float(costs.get("flops", -1.0))
        if flops < 0:
            continue
        return {"flops": flops,
                "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
                "raw": {k: float(v) for k, v in dict(costs).items()
                        if isinstance(v, (int, float))}}
    return None


def extract_xla_memory(compiled):
    """Compiled memory stats: argument/output/temp/alias bytes plus the
    derived ``peak_hbm_bytes`` (arg + out + temp - alias + code), or
    None when the backend doesn't report them."""
    if compiled is None:
        return None
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    fields = ("generated_code_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "temp_size_in_bytes")
    try:
        out = {f: int(getattr(mem, f)) for f in fields}
    except AttributeError:
        return None
    out["peak_hbm_bytes"] = (
        out["argument_size_in_bytes"] + out["output_size_in_bytes"]
        + out["temp_size_in_bytes"] - out["alias_size_in_bytes"]
        + out["generated_code_size_in_bytes"])
    return out


# ---------------------------------------------------------------------------
# static analyzer — jaxpr walk (exact shapes, backward included)
# ---------------------------------------------------------------------------

# pure data movement / layout: zero FLOPs whatever the shapes
_ZERO_FLOP_PRIMS = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "gather", "scatter", "squeeze", "expand_dims", "rev", "pad", "iota",
    "copy", "device_put", "stop_gradient", "split", "pjit_no_op",
})
# one pass over the operand, not the (reduced) output
_REDUCE_PREFIXES = ("reduce_", "cum", "arg")


def _aval_nbytes(aval):
    try:
        return int(math.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:        # key arrays / abstract tokens
        return 0


def _aval_numel(aval):
    try:
        return int(math.prod(aval.shape))
    except Exception:
        return 0


def _eqn_flops(eqn):
    """FLOPs of one jaxpr equation (sub-jaxprs handled by the caller)."""
    prim = eqn.primitive.name
    out_aval = eqn.outvars[0].aval
    if prim == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        k = 1
        for d in lhs_c:
            k *= int(lhs.shape[d])
        return 2 * _aval_numel(out_aval) * k
    if prim == "conv_general_dilated":
        rhs = eqn.invars[1].aval
        dn = eqn.params["dimension_numbers"]
        cout = int(rhs.shape[dn.rhs_spec[0]])
        if cout:
            # 2 * out_elems * (kernel_spatial * cin / groups)
            return 2 * _aval_numel(out_aval) * (_aval_numel(rhs) // cout)
        return 0
    if prim in _ZERO_FLOP_PRIMS:
        return 0
    if prim.startswith(_REDUCE_PREFIXES):
        return _aval_numel(eqn.invars[0].aval)
    # elementwise / transcendental / select / compare: one op per output
    return _aval_numel(out_aval)


def _sub_jaxprs(params):
    """Every jaxpr-valued param of an eqn (pjit's 'jaxpr', scan's
    'jaxpr', custom_jvp/vjp call_jaxpr, cond's 'branches' tuple, ...)."""
    from jax._src import core as jcore
    out = []
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if isinstance(x, jcore.ClosedJaxpr):
                out.append(x.jaxpr)
            elif isinstance(x, jcore.Jaxpr):
                out.append(x)
    return out


def analyze_jaxpr(closed):
    """Backend-independent cost walk of a (Closed)Jaxpr: total FLOPs,
    per-primitive attribution, and intermediate/output byte totals.

    Conventions (estimates, not a compiler): scan bodies multiply
    FLOPs by `length` (their intermediates count ONCE — only one
    iteration is live at a time); `while` bodies count one trip (the
    trip count is data); both `cond` branches count (upper bound);
    elementwise/transcendental ops count 1 FLOP per output element;
    pure layout ops count 0. Donation/aliasing is the caller's story
    (see `explain_entry`)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    per = {}
    totals = {"flops": 0, "intermediate_bytes": 0, "eqns": 0}

    def walk(jx, mult):
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            subs = _sub_jaxprs(eqn.params)
            if subs:
                inner = mult * int(eqn.params.get("length", 1)) \
                    if prim == "scan" else mult
                for s in subs:
                    walk(s, inner)
                # a sub-jaxpr eqn's own outvars are its inner results
                # rebound — counting them again would double activations
                continue
            f = _eqn_flops(eqn) * mult
            totals["flops"] += f
            totals["eqns"] += 1
            if f:
                per[prim] = per.get(prim, 0) + f
            # scan interiors: bytes counted once (see docstring)
            for ov in eqn.outvars:
                totals["intermediate_bytes"] += _aval_nbytes(ov.aval)

    walk(jaxpr, 1)
    out_bytes = sum(_aval_nbytes(v.aval) for v in jaxpr.outvars)
    totals["intermediate_bytes"] = max(
        0, totals["intermediate_bytes"] - out_bytes)
    return {"flops": int(totals["flops"]),
            "per_primitive": dict(sorted(per.items(),
                                         key=lambda kv: -kv[1])),
            "intermediate_bytes": int(totals["intermediate_bytes"]),
            "out_bytes": int(out_bytes),
            "eqns": totals["eqns"]}


# ---------------------------------------------------------------------------
# static analyzer — Block/OpDesc walk (fluid-level attribution)
# ---------------------------------------------------------------------------

def analyze_program(program, feeds=None, state=None, batch_size=None):
    """Fluid-level static attribution for a Program: per-op-type
    forward FLOPs (utils/model_stat's hand-count rules), parameter vs
    optimizer-state bytes, activation and feed bytes. Byte numbers
    prefer the live arrays (`state`/`feeds` — actual dtypes after bf16
    casts) and fall back to the declared var shapes with -1 batch dims
    resolved to `batch_size`."""
    from ..core.framework import dtype_itemsize
    from ..utils import model_stat

    if batch_size is None:
        batch_size = 1
        for v in (feeds or {}).values():
            shape = getattr(v, "shape", ())
            if len(shape) >= 1:
                batch_size = int(shape[0])
                break

    fwd_flops, per_op = model_stat.count_flops(program, batch_size)
    train = program.backward_marker() is not None
    param_names = {p.name for p in program.all_parameters()}

    if state:
        param_bytes = sum(array_nbytes(v) for n, v in state.items()
                          if n in param_names)
        optimizer_bytes = sum(array_nbytes(v) for n, v in state.items()
                              if n not in param_names)
    else:
        param_bytes = optimizer_bytes = 0
        for v in program.list_vars():
            if not v.persistable:
                continue
            b = v.nbytes(batch_size)
            if v.name in param_names:
                param_bytes += b
            else:
                optimizer_bytes += b

    if feeds:
        feed_bytes = sum(array_nbytes(v) for v in feeds.values())
    else:
        feed_bytes = sum(v.nbytes(batch_size)
                         for v in program.list_vars() if v.is_data)

    gb = program.global_block()
    activation_bytes = sum(
        v.nbytes(batch_size) for v in gb.vars.values()
        if not v.persistable and not v.is_data and v.shape)

    return {
        "batch_size": batch_size,
        "train": train,
        "fwd_flops": int(fwd_flops),
        # the classic hand-count convention: train step ~ 3x forward
        "flops": int(fwd_flops) * (3 if train else 1),
        "per_op_type": dict(sorted(per_op.items(), key=lambda kv: -kv[1])),
        "param_bytes": int(param_bytes),
        "optimizer_bytes": int(optimizer_bytes),
        "feed_bytes": int(feed_bytes),
        "activation_bytes": int(activation_bytes),
        "num_ops": len(gb.ops),
    }


# ---------------------------------------------------------------------------
# report assembly (Executor.explain's engine)
# ---------------------------------------------------------------------------

def explain_entry(step_fn, args, program=None, state=None, feeds=None,
                  labels=None, backend=None):
    """Full compile-plane report for one jitted entry.

    `backend=None` (auto) asks XLA first and falls back to the static
    analyzer per field; `backend=False` forces the static path (the
    deterministic answer on any backend); `backend=True` demands XLA's
    numbers and raises if the backend doesn't report them. The static
    analysis always runs — it is the cross-check column.

    Headline fields and their fallback chain:
      flops          xla cost_analysis -> jaxpr walk
      bytes_accessed xla cost_analysis -> arg + out + 2x intermediates
      peak_hbm_bytes xla memory_analysis -> arg + (out - donated) +
                     intermediates (donated state aliases in-place)
    """
    import jax

    xla_cost = xla_mem = None
    if backend is not False:
        try:
            lowered = step_fn.lower(*args)
            compiled = lowered.compile()
            xla_cost = extract_xla_cost(lowered=lowered, compiled=compiled)
            xla_mem = extract_xla_memory(compiled)
        except Exception:
            xla_cost = xla_mem = None
        if backend is True and (xla_cost is None or xla_mem is None):
            raise RuntimeError(
                f"backend={jax.default_backend()!r} reports no "
                f"{'cost' if xla_cost is None else 'memory'} analysis "
                f"for this entry; use backend=None for the static "
                f"fallback")

    jaxpr_rep = analyze_jaxpr(jax.make_jaxpr(step_fn)(*args))
    prog_rep = analyze_program(program, feeds=feeds, state=state) \
        if program is not None else None

    arg_bytes = sum(array_nbytes(a) for part in args
                    for a in jax.tree_util.tree_leaves(part))
    state_bytes = sum(array_nbytes(a) for a in
                      jax.tree_util.tree_leaves(state or {}))
    donated = state_bytes if (
        program is not None and program.backward_marker() is not None
        and state) else 0
    out_bytes = jaxpr_rep["out_bytes"]
    static_peak = arg_bytes + max(0, out_bytes - donated) \
        + jaxpr_rep["intermediate_bytes"]
    static_bytes = arg_bytes + out_bytes \
        + 2 * jaxpr_rep["intermediate_bytes"]

    flops = xla_cost["flops"] if xla_cost else jaxpr_rep["flops"]
    bytes_accessed = xla_cost["bytes_accessed"] if xla_cost \
        else static_bytes
    peak = xla_mem["peak_hbm_bytes"] if xla_mem else static_peak

    report = {
        "backend": jax.default_backend(),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "peak_hbm_bytes": peak,
        "source": {"flops": "xla" if xla_cost else "static",
                   "bytes": "xla" if xla_cost else "static",
                   "peak_hbm": "xla" if xla_mem else "static"},
        "xla": {"cost": xla_cost, "memory": xla_mem},
        "static": {"jaxpr": jaxpr_rep, "program": prog_rep,
                   "arg_bytes": int(arg_bytes),
                   "out_bytes": int(out_bytes),
                   "donated_bytes": int(donated),
                   "bytes_accessed_est": int(static_bytes),
                   "peak_hbm_bytes_est": int(static_peak)},
        "memory": {
            "param_bytes": prog_rep["param_bytes"] if prog_rep else None,
            "optimizer_bytes": prog_rep["optimizer_bytes"]
            if prog_rep else None,
            "feed_bytes": prog_rep["feed_bytes"] if prog_rep else None,
            "activation_bytes": prog_rep["activation_bytes"]
            if prog_rep else None,
            "peak_hbm_bytes": peak,
        },
    }
    if labels:
        report.update(labels)
    return report


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------

RESIDENT_KINDS = ("params", "optimizer", "kv_cache", "other")
# "host_ram" rows account host-memory commitments (the serving KV
# spill tier): real bytes a fleet sizes against, but deliberately NOT
# a resident kind — memory.total_bytes stays per-device HBM truth.
LEDGER_KINDS = RESIDENT_KINDS + ("peak_hbm", "host_ram")


def _agg(kind, acc, nbytes):
    """Rollup rule per kind: resident entries are disjoint buffers and
    sum; ``peak_hbm`` entries are per-(program, shapes) estimates over
    mostly the SAME buffers — the rollup is the worst case, not a sum."""
    return max(acc, nbytes) if kind == "peak_hbm" else acc + nbytes


class HBMLedger:
    """Process-wide account of device-memory commitments.

    Entries are keyed (component, name); `register` is an upsert so a
    component refreshing a number never duplicates a row; `retire`
    drops a component's rows (and its emptied ``memory.bytes`` gauge
    series) — a closed server or a cleared jit cache must never keep
    reporting freed bytes as live. Resident kinds sum into
    ``memory.total_bytes``; ``peak_hbm`` entries are estimates over
    mostly the same buffers and never sum — per component (one entry
    per compiled (program, shapes)) they aggregate as the MAX: the
    worst-case compiled step is the component's peak.
    """

    def __init__(self, registry=None):
        self._reg = registry if registry is not None else global_registry()
        self._lock = threading.Lock()
        self._entries = {}      # (component, name) -> entry dict

    def _gauges(self):
        return (self._reg.gauge("memory.bytes", _help("memory.bytes")),
                self._reg.gauge("memory.total_bytes",
                                _help("memory.total_bytes")),
                self._reg.gauge("memory.entries", _help("memory.entries")))

    def register(self, component, name, kind, nbytes, detail=None):
        """Upsert one entry; returns it. `kind` must be a LEDGER_KINDS
        member; `detail` is a small JSON-able dict for the /memory
        endpoint (dtype, shapes, counts)."""
        if kind not in LEDGER_KINDS:
            raise ValueError(
                f"unknown ledger kind {kind!r}; expected one of "
                f"{LEDGER_KINDS}")
        entry = {"component": str(component), "name": str(name),
                 "kind": kind, "bytes": int(nbytes),
                 "detail": dict(detail or {})}
        with self._lock:
            self._entries[(entry["component"], entry["name"])] = entry
            self._publish(entry["component"])
        return entry

    def retire(self, component, name=None):
        """Drop one entry (or, with name=None, every entry) of a
        component; removes gauge series that emptied. Idempotent."""
        component = str(component)
        with self._lock:
            if name is not None:
                self._entries.pop((component, str(name)), None)
            else:
                for key in [k for k in self._entries if k[0] == component]:
                    del self._entries[key]
            self._publish(component)

    def _publish(self, component):
        """Refresh the gauges for one component + the process totals.
        Caller holds the lock."""
        by_kind_g, total_g, entries_g = self._gauges()
        live = {}
        resident_total = 0
        for e in self._entries.values():
            if e["kind"] in RESIDENT_KINDS:
                resident_total += e["bytes"]
            if e["component"] == component:
                live[e["kind"]] = _agg(e["kind"], live.get(e["kind"], 0),
                                       e["bytes"])
        for kind in LEDGER_KINDS:
            if kind in live:
                by_kind_g.labels(component=component, kind=kind).set(
                    live[kind])
            else:
                by_kind_g.remove(component=component, kind=kind)
        total_g.set(resident_total)
        entries_g.set(len(self._entries))

    def component_bytes(self, component):
        """{kind: bytes} for one component's live entries."""
        component = str(component)
        with self._lock:
            out = {}
            for e in self._entries.values():
                if e["component"] == component:
                    out[e["kind"]] = _agg(e["kind"], out.get(e["kind"], 0),
                                          e["bytes"])
            return out

    def snapshot(self):
        """JSON-able view: process totals, per-kind and per-component
        rollups, and the raw entry list (the /memory endpoint body and
        ``Executor.get_stats()["memory"]["ledger"]``)."""
        with self._lock:
            entries = [dict(e, detail=dict(e["detail"]))
                       for e in self._entries.values()]
        by_kind, by_component = {}, {}
        resident = 0
        for e in entries:
            by_kind[e["kind"]] = _agg(e["kind"], by_kind.get(e["kind"], 0),
                                      e["bytes"])
            comp = by_component.setdefault(e["component"], {})
            comp[e["kind"]] = _agg(e["kind"], comp.get(e["kind"], 0),
                                   e["bytes"])
            if e["kind"] in RESIDENT_KINDS:
                resident += e["bytes"]
        return {"total_bytes": resident,
                "by_kind": by_kind,
                "by_component": by_component,
                "entries": sorted(entries, key=lambda e: (e["component"],
                                                          e["name"]))}

    def reset(self):
        """Drop everything (tests only)."""
        with self._lock:
            components = {e["component"] for e in self._entries.values()}
            self._entries.clear()
            for c in components:
                self._publish(c)


_LEDGER = HBMLedger()


def hbm_ledger():
    return _LEDGER


# ---------------------------------------------------------------------------
# recompile-storm detector
# ---------------------------------------------------------------------------

class RecompileStormWarning(UserWarning):
    """Raised (as a warning) when an already-warm Program keeps
    compiling fresh feed signatures at storm rate — almost always
    unbucketed dynamic shapes. See docs/performance.md."""


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_stacklevel():
    """stacklevel that attributes a warning to the first frame OUTSIDE
    paddle_tpu — the user's run()/run_async() call site, whatever entry
    path led here (run() is wrapped by compiler._run_maybe_compiled,
    run_async() is not, so no constant is right for both)."""
    # _getframe(1) is our caller (where warnings.warn runs) = level 1
    frame, level = sys._getframe(1), 1
    while frame is not None and frame.f_code.co_filename.startswith(
            _PKG_DIR):
        frame = frame.f_back
        level += 1
    return level


def _sig_str(shape, dtype):
    return f"{'x'.join(map(str, shape)) or 'scalar'}:{dtype}"


class RecompileTracker:
    """Per-executor jit-cache-miss historian.

    Every miss records its feed signature per program; past the warm
    threshold (`PADDLE_TPU_RECOMPILE_WARM` distinct signatures, default
    2) each further miss is a *recompile event* carrying a structured
    key diff against the nearest cached signature — which feed var
    changed shape/dtype, or whether the fetch/state signature moved
    instead. Events inside the rate window
    (`PADDLE_TPU_RECOMPILE_WINDOW_S`, default 60s) reaching the storm
    threshold (`PADDLE_TPU_RECOMPILE_STORM`, default 3) raise ONE
    `RecompileStormWarning` per burst (latched until the window
    drains). `PADDLE_TPU_RECOMPILE_DETECT=0` disables the tracker.

    Hot-path cost: zero on cache hits (never called); one small
    signature comparison per miss — next to the XLA compile a miss
    pays anyway, this is noise (perf/compile_sample.json pins it).
    """

    MAX_EVENTS = 64         # bounded postmortem ring per executor
    MAX_SIGNATURES = 64     # bounded per-program history: the nearest-
    #                         signature scan is O(history), and a
    #                         pathological unbucketed stream must not
    #                         grow it (or the diff cost) without limit

    def __init__(self, stats=None, warm=None, storm=None, window_s=None,
                 enabled=None, clock=None):
        import os
        env = os.environ.get
        self.enabled = (env("PADDLE_TPU_RECOMPILE_DETECT", "1") != "0"
                        if enabled is None else bool(enabled))
        self.warm = int(warm if warm is not None
                        else env("PADDLE_TPU_RECOMPILE_WARM", 2))
        self.storm = int(storm if storm is not None
                         else env("PADDLE_TPU_RECOMPILE_STORM", 3))
        self.window_s = float(
            window_s if window_s is not None
            else env("PADDLE_TPU_RECOMPILE_WINDOW_S", 60.0))
        self._stats = stats
        self._clock = clock if clock is not None else time.monotonic
        self._history = {}      # uid -> [(feed_sig, fetch, state, extra)]
        self._events = []       # bounded, newest last
        self._total_events = 0  # cumulative (the ring truncates at 64)
        self._window = []       # event timestamps inside the rate window
        self._storms = 0
        self._latched = False

    # -- bookkeeping --------------------------------------------------------
    def observe_miss(self, program_uid, program_label, feed_sig,
                     fetch_names, state_sig, step_id, extra_sig=()):
        """Record one jit-cache miss. Returns the event dict when this
        miss is a post-warm recompile (the caller threads its summary
        into the compile span's trace args), else None. `extra_sig` is
        the labeled tail of the caller's cache key ((name, value)
        pairs, e.g. program version and mesh) so a miss whose feeds
        never moved is attributed to what actually changed."""
        if not self.enabled:
            return None
        hist = self._history.setdefault(program_uid, [])
        event = None
        if hist and len(hist) >= self.warm:     # a diff needs a neighbor
            event = self._diff_event(program_uid, program_label, feed_sig,
                                     fetch_names, state_sig, step_id,
                                     extra_sig)
            self._record_event(event)
        hist.append((feed_sig, fetch_names, state_sig, extra_sig))
        del hist[:-self.MAX_SIGNATURES]
        return event

    def _diff_event(self, program_uid, program_label, feed_sig,
                    fetch_names, state_sig, step_id, extra_sig):
        now = dict((k, (s, d)) for k, s, d in feed_sig)
        best = None         # (n_changed, -recency, diff, nearest_sig)
        hist = self._history[program_uid]
        for age, (sig, fetch, state, extra) in enumerate(reversed(hist)):
            cached = dict((k, (s, d)) for k, s, d in sig)
            changed, added, removed = [], [], []
            for k, (shape, dtype) in now.items():
                if k not in cached:
                    added.append(k)
                elif cached[k] != (shape, dtype):
                    cs, cd = cached[k]
                    changed.append({
                        "var": k,
                        "from": _sig_str(cs, cd), "to": _sig_str(shape,
                                                                 dtype),
                        "kind": "dtype" if cs == shape else "shape"})
            removed = [k for k in cached if k not in now]
            n = len(changed) + len(added) + len(removed)
            key = (n, age)
            if best is None or key < best[0]:
                best = (key, changed, added, removed,
                        (sig, fetch, state, extra))
                if n == 0:      # identical feeds: no closer match exists
                    break
        _key, changed, added, removed, (near_sig, near_fetch, near_state,
                                        near_extra) = best
        if changed or added or removed:
            parts = [f"{c['var']}: {c['from']} -> {c['to']}"
                     for c in changed]
            parts += [f"+{k}" for k in added] + [f"-{k}" for k in removed]
            summary = "; ".join(parts)
        else:
            # identical feeds: name what in the rest of the cache key
            # actually moved (fetch list, persistable-state set, or the
            # caller's extra components — program version, mesh, ...)
            parts = []
            if tuple(near_fetch) != tuple(fetch_names):
                parts.append("fetch_list changed")
            if near_state != state_sig:
                parts.append("persistable state set changed")
            near_ex = dict(near_extra)
            for name, val in extra_sig:
                if name in near_ex and near_ex[name] != val:
                    parts.append(f"{name} changed "
                                 f"({near_ex[name]} -> {val})")
            summary = ("; ".join(parts)
                       or "cache key changed (cause not visible)")
        return {"step": int(step_id), "program": program_label,
                "changed": changed, "added": added, "removed": removed,
                "nearest": ";".join(_sig_str(s, d)
                                    for _k, s, d in near_sig) or "nofeeds",
                "summary": summary, "ts": self._clock()}

    def _record_event(self, event):
        self._events.append(event)
        del self._events[:-self.MAX_EVENTS]
        self._total_events += 1
        if self._stats is not None:
            self._stats.count("executor.recompile.events")
        now = event["ts"]
        self._window = [t for t in self._window
                        if now - t <= self.window_s]
        self._window.append(now)
        if self._stats is not None:
            self._stats.set_gauge("executor.recompile.window_events",
                                  len(self._window))
        if len(self._window) >= self.storm:
            if not self._latched:
                self._latched = True
                self._storms += 1
                if self._stats is not None:
                    self._stats.count("executor.recompile.storms")
                warnings.warn(RecompileStormWarning(
                    f"recompile storm: {len(self._window)} fresh XLA "
                    f"compiles of already-warm program(s) within "
                    f"{self.window_s:.0f}s (latest: {event['program']}, "
                    f"key diff vs nearest cached signature: "
                    f"{event['summary']}). Every distinct feed "
                    f"shape/dtype is a full recompile — bucket feeds "
                    f"with core.bucketing.FeedBucketer "
                    f"(run_async(bucketer=...)/run_pipelined) or pad "
                    f"host-side; see docs/performance.md and "
                    f"docs/observability.md 'Compile & memory'."),
                    stacklevel=_user_stacklevel())
        else:
            self._latched = False

    # -- surfaces -----------------------------------------------------------
    def events(self, program=None):
        """Recorded recompile events, newest last; `program` filters by
        the event's program label."""
        evs = self._events if program is None else \
            [e for e in self._events if e["program"] == program]
        return [dict(e) for e in evs]

    def snapshot(self):
        now = self._clock()
        window = [t for t in self._window if now - t <= self.window_s]
        return {"enabled": self.enabled,
                # cumulative, NOT len(self._events): the postmortem ring
                # truncates at MAX_EVENTS but the count must keep pace
                # with the executor.recompile.events counter
                "events": self._total_events,
                "storms": self._storms,
                "window_events": len(window),
                "warm_threshold": self.warm,
                "storm_threshold": self.storm,
                "window_s": self.window_s,
                "signatures": {str(uid): len(sigs)
                               for uid, sigs in self._history.items()},
                "last_events": [dict(e) for e in self._events[-5:]]}

    def reset(self):
        """Forget everything (clear_caches: freed entries make the next
        compiles cold again, not recompiles)."""
        self._history.clear()
        self._events.clear()
        self._total_events = 0
        self._window.clear()
        self._latched = False
        if self._stats is not None:
            self._stats.set_gauge("executor.recompile.window_events", 0)
