"""Runtime observability: metrics, tracing, and component stats.

Seven pieces, wired through the execution stack:

- metrics.py — process-wide MetricsRegistry (counters / gauges /
  ms-histograms; JSON + Prometheus export; METRIC_SPECS namespace lint).
- tracing.py — Chrome trace_event recorder (Perfetto-loadable), off by
  default, enabled by paddle_tpu.profiler; bounded drop-oldest ring.
- sketch.py — deterministic mergeable quantile digest (the SLO
  p50/p90/p99 backend; no external deps).
- serving_telemetry.py — request-level serving telemetry: lifecycle
  span trees, SLO digests, and the fault flight recorder
  (GenerationServer wires it; GuardedTrainer reuses the recorder).
- exporter.py — stdlib HTTP /metrics (Prometheus), /healthz, /slo,
  /memory endpoint any component mounts via serve_metrics(port=...).
- compile_insight.py — the compile plane: XLA cost/memory extraction
  with static-analyzer fallback, the process-wide HBM ledger
  (memory.* gauges), and the recompile-storm detector the Executor
  wires into its jit-cache miss path (Executor.explain's engine).
- ComponentStats (here) — the per-component view an instrumented object
  (the Executor) holds: every update lands in BOTH the component's
  private registry (so Executor.get_stats() answers per-instance
  questions) and the process-wide one (so an exporter scrapes one place).

jax's own compile-time telemetry is bridged in: a jax.monitoring
duration listener feeds `executor.backend_compile_ms`, so genuine XLA
backend-compile seconds are visible next to the framework's wall-clock
compile span. See docs/observability.md.
"""

import contextlib
import time

from . import metrics
from . import tracing
from . import sketch
from .metrics import (MetricsRegistry, global_registry, METRIC_SPECS,
                      DEFAULT_MS_BUCKETS)
from .sketch import QuantileSketch
from .tracing import TraceRecorder, get_recorder

__all__ = ["metrics", "tracing", "sketch", "MetricsRegistry",
           "global_registry", "METRIC_SPECS", "DEFAULT_MS_BUCKETS",
           "QuantileSketch", "TraceRecorder", "get_recorder",
           "ComponentStats"]

# serving_telemetry, exporter and compile_insight import lazily from
# here (they need _help below); they are reached as
# paddle_tpu.observability.<module> by the serving engine, the Executor
# and tests without being imported at package import time (the exporter
# pulls http.server in, which the training path never needs;
# compile_insight pulls jax internals the bare metrics path never
# needs).


class ComponentStats:
    """Local + global metrics fan-out for one instrumented component.

    `gauge_labels` (e.g. {"executor": "exe0"}) distinguish this
    component's gauge series in the process-wide registry — two live
    Executors must not stomp each other's cache-size gauges. Counters
    and histograms aggregate unlabeled globally (per-instance numbers
    come from the local registry via get_stats())."""

    def __init__(self, gauge_labels=None):
        self.local = MetricsRegistry()
        self.gauge_labels = dict(gauge_labels or {})
        # name -> (local metric, global metric) handles, resolved once:
        # the cached-step hot path calls count/observe ~10x per run and
        # must not pay registry lock + name-regex on each (the Executor
        # optimized this path at the ~0.5ms level). reset() zeroes values
        # in place, so cached handles stay valid across it.
        self._handles = {}

    def _pair(self, name, method):
        pair = self._handles.get((name, method))
        if pair is None:
            make_local = getattr(self.local, method)
            make_global = getattr(global_registry(), method)
            pair = (make_local(name, _help(name)),
                    make_global(name, _help(name)))
            self._handles[(name, method)] = pair
        return pair

    def count(self, name, n=1):
        local, glob = self._pair(name, "counter")
        local.inc(n)
        glob.inc(n)

    def observe(self, name, ms, labels=None):
        local, glob = self._pair(name, "histogram")
        (local.labels(**labels) if labels else local).observe(ms)
        # the global side aggregates UNLABELED: per-(program, shapes)
        # label sets are unbounded in a long-lived process, so labeled
        # series live only in this component's registry, which dies with
        # the component (bounded-cardinality labels like the jax event
        # names on backend_compile_ms go to the global registry directly)
        glob.observe(ms)

    def set_gauge(self, name, value):
        local, glob = self._pair(name, "gauge")
        local.set(value)
        (glob.labels(**self.gauge_labels) if self.gauge_labels
         else glob).set(value)

    def drop_gauges(self, *names):
        """Zero local gauges and REMOVE this component's global gauge
        series — a closed executor must not report stale cache sizes
        forever from a long-lived process."""
        for name in names:
            m = self.local.get(name)
            if m is not None:
                m.reset()
            g = global_registry().get(name)
            if g is not None:
                if self.gauge_labels:
                    g.remove(**self.gauge_labels)
                else:
                    g.reset()

    @contextlib.contextmanager
    def span(self, trace_name, metric_name, trace_args=None):
        """Time a region into metric_name (both registries) and, when a
        capture is live, into the global Chrome-trace recorder. The
        metric observes even when the body raises (the trace recorder
        records the event in its own finally) so timeline and histograms
        never disagree about what happened."""
        t0 = time.perf_counter()
        try:
            with get_recorder().span(trace_name, cat="executor",
                                     args=trace_args):
                yield
        finally:
            self.observe(metric_name, (time.perf_counter() - t0) * 1e3)

    def reset(self):
        self.local.reset()


_HELP = {name: help for name, _kind, help in METRIC_SPECS}


def _help(name):
    return _HELP.get(name, "")


_monitoring_installed = False


def _install_jax_monitoring():
    """Bridge jax.monitoring duration events (XLA backend compile time
    etc.) into the global registry. Idempotent; never raises — the
    runtime must work on jax builds without the monitoring module."""
    global _monitoring_installed
    if _monitoring_installed:
        return
    try:
        from jax import monitoring as _jm

        def _on_duration(event, duration, **kwargs):
            try:
                if "compile" in event:
                    global_registry().histogram(
                        "executor.backend_compile_ms",
                        _help("executor.backend_compile_ms")).labels(
                            event=event.strip("/").rsplit("/", 1)[-1]
                        ).observe(duration * 1e3)
            except Exception:
                pass    # telemetry must never break a compile

        _jm.register_event_duration_secs_listener(_on_duration)
        _monitoring_installed = True
    except Exception:
        pass


_install_jax_monitoring()
