"""Structured trace recorder: Chrome trace_event JSON.

Host-side spans (Executor step phases, profiler.record_event regions,
per-op trace-time dispatch) land here as complete ('X') events and export
in the chrome://tracing / Perfetto schema, the same format
utils/timeline.py emits for legacy profiler records. Device-side op
timelines still come from the jax.profiler trace directory (XProf);
because ops/__init__.py wraps every dispatch in jax.named_scope, the XLA
HLO op names in that device trace line up with the framework spans here.

The recorder is OFF by default: a disabled span() costs one attribute
check, so the Executor can call it unconditionally on the hot path.
profiler.start_profiler() (or recorder.start()) turns it on.

The event buffer is a bounded ring (drop-oldest): a long-lived
GenerationServer with tracing on keeps the most recent
`PADDLE_TPU_TRACE_BUFFER` events (default 200k, ~100 MB of JSON at the
far end) instead of growing host memory without limit. Drops are counted
in the `tracing.dropped_events` metric and reported in the export's
otherData so a truncated capture is never mistaken for a complete one.

Besides thread-keyed spans, events can target a named *track* (the
`track=` argument): serving request lifecycles render one Perfetto track
per decode slot instead of interleaving on the engine thread's row.
"""

import collections
import contextlib
import json
import os
import threading
import time
import warnings

__all__ = ["TraceRecorder", "get_recorder", "DEFAULT_MAX_EVENTS"]

DEFAULT_MAX_EVENTS = 200_000


def _default_max_events():
    raw = os.environ.get("PADDLE_TPU_TRACE_BUFFER", "").strip()
    if not raw:
        return DEFAULT_MAX_EVENTS
    try:
        n = int(raw)
        if n <= 0:
            raise ValueError(n)
    except ValueError:
        # a typo'd knob must not silently shrink the ring to 1 event
        # (or silently revert to the default): warn and use the default
        warnings.warn(
            f"ignoring bad PADDLE_TPU_TRACE_BUFFER={raw!r} (want a "
            f"positive event count); using {DEFAULT_MAX_EVENTS}",
            RuntimeWarning, stacklevel=2)
        return DEFAULT_MAX_EVENTS
    return n


class TraceRecorder:
    def __init__(self, max_events=None):
        self._lock = threading.Lock()
        self._explicit_max = max_events is not None
        self._max_events = int(max_events if max_events is not None
                               else _default_max_events())
        self._events = collections.deque(maxlen=self._max_events)
        self._dropped = 0
        self._dropped_counter = None    # lazy: metrics must not import us
        self._enabled = False
        self._t0 = 0.0          # perf_counter origin of ts=0
        self._epoch0 = 0.0      # wall clock at start() (metadata only)
        self._pid = os.getpid()

    @property
    def enabled(self):
        return self._enabled

    @property
    def max_events(self):
        return self._max_events

    @property
    def dropped(self):
        """Events dropped by the ring since the last start()/clear()."""
        return self._dropped

    def start(self, origin=None):
        """Begin a capture (clears any previous one). `origin` pins the
        perf_counter instant that maps to ts=0 — the fleet tracer
        starts every replica's recorder against ONE shared origin so
        cross-replica stamps merge into a single comparable timeline
        (observability/fleet_trace.py); default is "now"."""
        with self._lock:
            # the global recorder is built at import time; honour a
            # PADDLE_TPU_TRACE_BUFFER set programmatically afterwards by
            # re-reading the knob at capture start (explicit max_events
            # passed to the constructor still wins)
            if not self._explicit_max:
                n = _default_max_events()
                if n != self._max_events:
                    self._max_events = n
                    self._events = collections.deque(maxlen=n)
            self._events.clear()
            self._dropped = 0
            self._t0 = (time.perf_counter() if origin is None
                        else float(origin))
            self._epoch0 = time.time()
            self._enabled = True

    def stop(self):
        self._enabled = False

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def events(self):
        with self._lock:
            return list(self._events)

    # -- recording ----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name, cat="host", args=None):
        """Time a region into a complete event. No-op while disabled."""
        if not self._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            if self._enabled:   # capture may have stopped mid-span: drop
                self._emit(name, cat, (t0 - self._t0) * 1e6,
                           (t1 - t0) * 1e6, args)

    def complete(self, name, start, end, cat="host", args=None, track=None):
        """Record a complete event from explicit perf_counter stamps.

        Request lifecycle span trees are emitted retroactively (the whole
        tree is known only at retirement), so they cannot use the span()
        context manager. `track` names a dedicated Perfetto track (e.g.
        "serving slot 0") instead of keying on the calling thread.

        Stamps predating the capture are clamped to the capture origin:
        a request already in flight when the capture started would
        otherwise emit ts < 0, which Perfetto renders outside the
        viewport — its pre-capture portion is truncated instead."""
        if not self._enabled:
            return
        start_us = max(start - self._t0, 0.0) * 1e6
        end_us = max(end - self._t0, 0.0) * 1e6
        self._emit(name, cat, start_us,
                   max(end_us - start_us, 0.0), args, tid=track)

    def instant(self, name, cat="host", args=None, ts=None, track=None):
        if not self._enabled:
            return
        self._append({
            "ph": "i", "s": "t", "cat": cat, "name": name,
            "pid": self._pid,
            "tid": track if track is not None else threading.get_ident(),
            "ts": max((ts if ts is not None else time.perf_counter())
                      - self._t0, 0.0) * 1e6,
            "args": args or {}})

    def _emit(self, name, cat, ts_us, dur_us, args, tid=None):
        self._append({"ph": "X", "cat": cat, "name": name, "pid": self._pid,
                      "tid": tid if tid is not None
                      else threading.get_ident(),
                      "ts": round(ts_us, 3), "dur": round(dur_us, 3),
                      "args": args or {}})

    def _append(self, evt):
        with self._lock:
            if len(self._events) == self._max_events:
                self._dropped += 1
                if self._dropped_counter is None:
                    from .metrics import global_registry
                    self._dropped_counter = global_registry().counter(
                        "tracing.dropped_events",
                        "trace events dropped by the bounded ring buffer "
                        "(drop-oldest)")
                self._dropped_counter.inc()
            self._events.append(evt)    # deque(maxlen) evicts the oldest

    # -- export -------------------------------------------------------------
    def to_chrome(self):
        """{"traceEvents": [...]} with thread ids renumbered small and
        process/thread metadata ('M') events prepended. String tids
        (named tracks) keep their name on the Perfetto track label."""
        with self._lock:
            events = [dict(e) for e in self._events]
            dropped = self._dropped
        tids = {}
        for e in events:
            e["tid"] = tids.setdefault(e["tid"], len(tids))
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "paddle_tpu host"}}]
        for raw, small in tids.items():
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": small,
                         "args": {"name": raw if isinstance(raw, str)
                                  else f"thread {raw}"}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"start_epoch_s": self._epoch0,
                              "dropped_events": dropped,
                              "max_events": self._max_events}}

    def save(self, path, pretty=False):
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f, indent=2 if pretty else None,
                      separators=None if pretty else (",", ":"))


_GLOBAL = TraceRecorder()


def get_recorder():
    return _GLOBAL
