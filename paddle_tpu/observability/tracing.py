"""Structured trace recorder: Chrome trace_event JSON.

Host-side spans (Executor step phases, profiler.record_event regions,
per-op trace-time dispatch) land here as complete ('X') events and export
in the chrome://tracing / Perfetto schema, the same format
utils/timeline.py emits for legacy profiler records. Device-side op
timelines still come from the jax.profiler trace directory (XProf);
because ops/__init__.py wraps every dispatch in jax.named_scope, the XLA
HLO op names in that device trace line up with the framework spans here.

The recorder is OFF by default: a disabled span() costs one attribute
check, so the Executor can call it unconditionally on the hot path.
profiler.start_profiler() (or recorder.start()) turns it on.
"""

import contextlib
import json
import os
import threading
import time

__all__ = ["TraceRecorder", "get_recorder"]


class TraceRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._enabled = False
        self._t0 = 0.0          # perf_counter origin of ts=0
        self._epoch0 = 0.0      # wall clock at start() (metadata only)
        self._pid = os.getpid()

    @property
    def enabled(self):
        return self._enabled

    def start(self):
        """Begin a capture (clears any previous one)."""
        with self._lock:
            self._events = []
            self._t0 = time.perf_counter()
            self._epoch0 = time.time()
            self._enabled = True

    def stop(self):
        self._enabled = False

    def clear(self):
        with self._lock:
            self._events = []

    def events(self):
        with self._lock:
            return list(self._events)

    # -- recording ----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name, cat="host", args=None):
        """Time a region into a complete event. No-op while disabled."""
        if not self._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            if self._enabled:   # capture may have stopped mid-span: drop
                self._emit(name, cat, (t0 - self._t0) * 1e6,
                           (t1 - t0) * 1e6, args)

    def instant(self, name, cat="host", args=None):
        if not self._enabled:
            return
        with self._lock:
            self._events.append({
                "ph": "i", "s": "t", "cat": cat, "name": name,
                "pid": self._pid, "tid": threading.get_ident(),
                "ts": (time.perf_counter() - self._t0) * 1e6,
                "args": args or {}})

    def _emit(self, name, cat, ts_us, dur_us, args):
        evt = {"ph": "X", "cat": cat, "name": name, "pid": self._pid,
               "tid": threading.get_ident(),
               "ts": round(ts_us, 3), "dur": round(dur_us, 3),
               "args": args or {}}
        with self._lock:
            self._events.append(evt)

    # -- export -------------------------------------------------------------
    def to_chrome(self):
        """{"traceEvents": [...]} with thread ids renumbered small and
        process/thread metadata ('M') events prepended."""
        with self._lock:
            events = [dict(e) for e in self._events]
        tids = {}
        for e in events:
            e["tid"] = tids.setdefault(e["tid"], len(tids))
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "paddle_tpu host"}}]
        for raw, small in tids.items():
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": small,
                         "args": {"name": f"thread {raw}"}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"start_epoch_s": self._epoch0}}

    def save(self, path, pretty=False):
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f, indent=2 if pretty else None,
                      separators=None if pretty else (",", ":"))


_GLOBAL = TraceRecorder()


def get_recorder():
    return _GLOBAL
