"""Fleet health signals, part 2: the declarative alert rule engine.

`check_slo` can say "the burn rate is 3.1 right now"; nothing in the
stack can say "and it has been for two minutes, page someone" — or the
opposite, "that was a one-heartbeat blip, stand down". This module is
that judgment layer: a set of declarative rules over SeriesStore
series (observability/timeseries.py), evaluated once per sample tick
with the SAME injected clock that produced the points, so a chaos
storm replays to an identical alert timeline.

Rule kinds (AlertRule classmethod constructors):

- ``threshold(series, op, threshold, for_s=...)`` — the newest point
  must satisfy ``value OP threshold`` continuously for ``for_s``
  seconds before the alert fires (a streak, tracked per rule; any
  non-satisfying point resets it).
- ``delta(series, threshold, window_s=...)`` — the change across the
  trailing window (newest minus oldest point in window) crosses the
  threshold: leak and runaway detection.
- ``absence(series, window_s=...)`` — no new point for ``window_s``:
  staleness, the "replica stopped reporting" signal. Grace-gated on
  the rule's first evaluation so a young fleet isn't instantly stale.
- ``burn_rate(series, threshold, fast_s, slow_s)`` — the SRE
  multi-window form: the MEAN over the fast window AND the mean over
  the slow window must both exceed the threshold before paging. The
  fast window gives detection latency, the slow window proves the
  burn is sustained — a single-heartbeat spike satisfies neither
  alone, so blips don't flap the pager (docs/observability.md has the
  window math).

Lifecycle is latched: ``ok → firing → resolved → firing → ...``. An
alert that has ever fired stays in the payload with its fired/resolved
stamps and counts — the /alerts body is a record, not just a snapshot.
Transitions move ``serving.alerts.{fired,resolved,active}`` and invoke
``on_event`` — the router mirrors that into the fleet trace track and
fleet FlightRecorder as instants (the `_flight_event` path), so alert
history lands in the same postmortem artifacts as the storm that
caused it. Served at ``/alerts`` (exporter.py) — the ROADMAP-5
autoscaler's input.
"""

import operator
import threading

from .metrics import global_registry

__all__ = ["AlertRule", "AlertManager", "empty_alerts"]

SCHEMA = "paddle_tpu.alerts/1"

_OPS = {">": operator.gt, ">=": operator.ge,
        "<": operator.lt, "<=": operator.le}


def empty_alerts():
    """The ``paddle_tpu.alerts/1`` payload with no alert plane behind
    it — the /alerts body a component WITHOUT a signal plane serves
    (exporter.py); AlertManager.payload() builds on it."""
    return {"schema": SCHEMA, "label": None, "rules": 0,
            "evaluations": 0, "active": 0, "alerts": []}


class AlertRule:
    """One declarative rule over one series. Build via the classmethod
    constructors — they pin the per-kind parameter set."""

    KINDS = ("threshold", "delta", "absence", "burn_rate")

    def __init__(self, kind, name, series, op=">", threshold=0.0,
                 for_s=0.0, window_s=0.0, fast_s=0.0, slow_s=0.0):
        if kind not in self.KINDS:
            raise ValueError(f"unknown rule kind {kind!r}; "
                             f"expected one of {self.KINDS}")
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r}; "
                             f"expected one of {tuple(_OPS)}")
        if kind == "burn_rate" and not 0 < fast_s <= slow_s:
            raise ValueError("burn_rate needs 0 < fast_s <= slow_s, "
                             f"got fast_s={fast_s} slow_s={slow_s}")
        self.kind = kind
        self.name = name
        self.series = series
        self.op = op
        self.threshold = float(threshold)
        self.for_s = float(for_s)
        self.window_s = float(window_s)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)

    @classmethod
    def threshold_rule(cls, name, series, threshold, op=">",
                       for_s=0.0):
        return cls("threshold", name, series, op=op,
                   threshold=threshold, for_s=for_s)

    @classmethod
    def delta(cls, name, series, threshold, op=">", window_s=60.0):
        return cls("delta", name, series, op=op, threshold=threshold,
                   window_s=window_s)

    @classmethod
    def absence(cls, name, series, window_s=60.0):
        return cls("absence", name, series, window_s=window_s)

    @classmethod
    def burn_rate(cls, name, series, threshold, fast_s, slow_s):
        return cls("burn_rate", name, series, threshold=threshold,
                   fast_s=fast_s, slow_s=slow_s)

    def to_dict(self):
        d = {"kind": self.kind, "name": self.name,
             "series": self.series}
        if self.kind == "threshold":
            d.update(op=self.op, threshold=self.threshold,
                     for_s=self.for_s)
        elif self.kind == "delta":
            d.update(op=self.op, threshold=self.threshold,
                     window_s=self.window_s)
        elif self.kind == "absence":
            d.update(window_s=self.window_s)
        else:
            d.update(threshold=self.threshold, fast_s=self.fast_s,
                     slow_s=self.slow_s)
        return d


class _AlertState:
    """One rule's latched lifecycle record."""

    __slots__ = ("rule", "state", "fired_at", "resolved_at",
                 "fired_count", "resolved_count", "last_value",
                 "streak_since", "first_eval")

    def __init__(self, rule):
        self.rule = rule
        self.state = "ok"
        self.fired_at = None
        self.resolved_at = None
        self.fired_count = 0
        self.resolved_count = 0
        self.last_value = None
        self.streak_since = None     # threshold streak anchor
        self.first_eval = None       # absence grace anchor

    def to_dict(self):
        return {"name": self.rule.name, "state": self.state,
                "rule": self.rule.to_dict(),
                "fired_at": self.fired_at,
                "resolved_at": self.resolved_at,
                "fired_count": self.fired_count,
                "resolved_count": self.resolved_count,
                "last_value": self.last_value}


def _window_points(points, t, window_s):
    """Points with stamp in [t - window_s, t], oldest first."""
    lo = t - window_s
    return [(pt, v) for pt, v in points if pt >= lo]


class AlertManager:
    """Evaluates rules against one SeriesStore on each sample tick.

    `on_event(kind, alert, t)` (kind in {"fired", "resolved"}) is the
    mirror hook — the router points it at its `_flight_event` path so
    transitions become fleet-trace instants and flight records."""

    def __init__(self, store, rules=(), label=None, on_event=None):
        self.store = store
        self.label = label
        self.on_event = on_event
        self._alerts = {}           # rule name -> _AlertState
        self._lock = threading.Lock()
        self._evaluations = 0
        reg = global_registry()
        self._m_fired = reg.counter(
            "serving.alerts.fired", "alert rule transitions to firing")
        self._m_resolved = reg.counter(
            "serving.alerts.resolved",
            "alert rule transitions firing -> resolved")
        self._g_active = reg.gauge(
            "serving.alerts.active", "alert rules currently firing")
        self._labels = {"manager": label} if label is not None else {}
        for r in rules:
            self.add_rule(r)

    def add_rule(self, rule):
        with self._lock:
            if rule.name in self._alerts:
                raise ValueError(f"duplicate alert rule {rule.name!r}")
            self._alerts[rule.name] = _AlertState(rule)

    # -- condition evaluation -----------------------------------------------
    def _condition(self, st, t):
        """(condition_holds, observed_value) for one rule at t."""
        rule = st.rule
        if rule.kind == "absence":
            if st.first_eval is None:
                st.first_eval = t
            latest = self.store.latest(rule.series)
            last_t = latest[0] if latest is not None else st.first_eval
            stale_s = t - last_t
            return stale_s >= rule.window_s, round(stale_s, 6)
        points = self.store.series(rule.series)
        if rule.kind == "threshold":
            if not points:
                st.streak_since = None
                return False, None
            pt, v = points[-1]
            if not _OPS[rule.op](v, rule.threshold):
                st.streak_since = None
                return False, v
            if st.streak_since is None:
                st.streak_since = pt
            return t - st.streak_since >= rule.for_s, v
        if rule.kind == "delta":
            win = _window_points(points, t, rule.window_s)
            if len(win) < 2:
                return False, None
            change = win[-1][1] - win[0][1]
            return _OPS[rule.op](change, rule.threshold), \
                round(change, 6)
        # burn_rate: both windows' means must exceed the threshold
        fast = _window_points(points, t, rule.fast_s)
        slow = _window_points(points, t, rule.slow_s)
        if not fast or not slow:
            return False, None
        mean_fast = sum(v for _t, v in fast) / len(fast)
        mean_slow = sum(v for _t, v in slow) / len(slow)
        return (mean_fast > rule.threshold
                and mean_slow > rule.threshold), round(mean_fast, 6)

    # -- tick ---------------------------------------------------------------
    def evaluate(self, t):
        """One evaluation tick at caller-supplied t. Returns the list
        of (kind, alert_dict) transition events this tick produced."""
        events = []
        with self._lock:
            self._evaluations += 1
            for st in self._alerts.values():
                holds, value = self._condition(st, t)
                st.last_value = value
                if holds and st.state != "firing":
                    st.state = "firing"
                    st.fired_at = round(t, 6)
                    st.fired_count += 1
                    events.append(("fired", st.to_dict()))
                elif not holds and st.state == "firing":
                    st.state = "resolved"
                    st.resolved_at = round(t, 6)
                    st.resolved_count += 1
                    events.append(("resolved", st.to_dict()))
            active = sum(1 for s in self._alerts.values()
                         if s.state == "firing")
            if self._labels:
                self._g_active.labels(**self._labels).set(active)
            self._g_active.set(active)
        for kind, alert in events:
            m = self._m_fired if kind == "fired" else self._m_resolved
            m.inc()
            if self.on_event is not None:
                self.on_event(kind, alert, t)
        return events

    # -- read side ----------------------------------------------------------
    @property
    def active(self):
        with self._lock:
            return sorted(s.rule.name for s in self._alerts.values()
                          if s.state == "firing")

    def state(self, name):
        with self._lock:
            return self._alerts[name].state

    def payload(self):
        """The /alerts body: the empty_alerts shape, filled in.
        Firing alerts sort first so the autoscaler reads the pageable
        set off the top."""
        order = {"firing": 0, "resolved": 1, "ok": 2}
        with self._lock:
            alerts = sorted((s.to_dict() for s in
                             self._alerts.values()),
                            key=lambda a: (order[a["state"]],
                                           a["name"]))
            return dict(empty_alerts(), label=self.label,
                        rules=len(self._alerts),
                        evaluations=self._evaluations,
                        active=sum(1 for a in alerts
                                   if a["state"] == "firing"),
                        alerts=alerts)

    def stats(self):
        """Cheap counters for get_stats() embedding (no alert list)."""
        with self._lock:
            return {"rules": len(self._alerts),
                    "active": sum(1 for s in self._alerts.values()
                                  if s.state == "firing"),
                    "evaluations": self._evaluations}

    def drop_gauges(self):
        """Retire this manager's active-alert series (router close
        path — a dead router must not report stale alert gauges)."""
        if self._labels:
            self._g_active.remove(**self._labels)
