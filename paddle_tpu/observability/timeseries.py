"""Fleet health signals, part 1: the metric time-series store.

Everything the observability stack exposed before this module is
instantaneous — a gauge is its last value, `check_slo` answers only
"now", and `SLOTracker` keeps one completed window. The ROADMAP's
autoscaler ("spawn/retire replica slots from live check_slo burn
rates") and the alert engine (observability/alerts.py) both need
HISTORY: is the burn rate rising, falling, or flapping?

`SeriesStore` is that history: a dict of bounded drop-oldest rings of
``(t, value)`` points, one ring per series key. Points arrive three
ways, all injected-clock-safe (no wall clocks, no RNG — callers pass
``t`` from the same clock that drives the serving tier):

- ``observe(name, t, value)`` / ``observe_many(t, pairs)`` — direct
  appends. The engine's ServingTelemetry feeds per-iteration scalars
  (step_ms, queue depth, block occupancy) and every completed SLO
  window's quantiles this way; the router feeds windowed burn rates.
- ``sample(t, registry=...)`` — one sampling tick over a
  MetricsRegistry: every gauge series under the configured name
  prefixes becomes a point, and every counter becomes a RATE point
  (delta since the previous tick / elapsed seconds, key suffixed
  ``:rate``) — a counter's absolute value is monotone noise on a
  chart, its slope is the signal. The router calls this once per
  heartbeat.

Series keys are Prometheus-flavored: ``name`` for the unlabeled
series, ``name{k=v,...}`` (sorted) for labeled children. Total key
cardinality is bounded (``max_series``); series beyond the cap are
dropped and counted, never silently absorbed.

Fleet merging follows the PR 14 dead-snapshot idiom exactly
(fleet_trace.FleetTracer): `FleetSeriesStore` tracks each replica's
live store by slot name + generation, freezes a dying replica's
payload into a bounded snapshot ring BEFORE the slot is resurrected
with a fresh store, and `merged()` emits fleet + dead + live sources
in one payload — a killed replica's history survives into the merged
``/series`` view, and a dropped snapshot marks the payload truncated.

Served at ``/series`` (exporter.py) and dumpable beside the Perfetto
trace via ``FleetRouter.dump_signals``. Metrics:
``serving.series.{points,dropped_points}`` (docs/observability.md
"Fleet health signals").
"""

import collections
import json
import threading

from .metrics import global_registry

__all__ = ["SeriesStore", "FleetSeriesStore", "empty_series",
           "series_key"]

SCHEMA = "paddle_tpu.series/1"
FLEET_SCHEMA = "paddle_tpu.series_fleet/1"


def empty_series():
    """The ``paddle_tpu.series/1`` payload with no store behind it —
    the /series body a component WITHOUT a signal plane serves
    (exporter.py). One definition of the schema's empty shape, same
    contract as fleet_trace.empty_trace_ring."""
    return {"schema": SCHEMA, "label": None, "capacity": 0,
            "points": 0, "dropped_points": 0, "dropped_series": 0,
            "series": {}}


def series_key(name, labels=None):
    """Prometheus-flavored series key: ``name`` unlabeled,
    ``name{k=v,...}`` (keys sorted) otherwise."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class _Ring:
    """One series: a bounded drop-oldest ring of (t, value) points."""

    __slots__ = ("points", "dropped")

    def __init__(self, capacity):
        self.points = collections.deque(maxlen=capacity)
        self.dropped = 0

    def append(self, t, v):
        if len(self.points) == self.points.maxlen:
            self.dropped += 1
        self.points.append((t, v))


class SeriesStore:
    """Bounded rings of (t, value) points keyed by series name.

    Callers own the clock: every entry point takes ``t`` explicitly,
    so a chaos-driven injected clock produces bit-identical stores on
    replay. Thread-safe (router heartbeat thread + engine callback
    threads feed one store)."""

    def __init__(self, capacity=512, max_series=256,
                 prefixes=("serving.",), label=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self.prefixes = tuple(prefixes)
        self.label = label
        self._rings = {}                 # key -> _Ring
        self._prev = {}                  # counter key -> (t, total)
        self._lock = threading.Lock()
        self._points_total = 0
        self._dropped_series = 0
        reg = global_registry()
        self._m_points = reg.counter(
            "serving.series.points",
            "time-series points recorded (all stores)")
        self._m_dropped = reg.counter(
            "serving.series.dropped_points",
            "time-series points evicted by ring wrap (all stores)")

    # -- direct appends -----------------------------------------------------
    def _ring(self, key):
        ring = self._rings.get(key)
        if ring is None:
            if len(self._rings) >= self.max_series:
                self._dropped_series += 1
                return None
            ring = self._rings[key] = _Ring(self.capacity)
        return ring

    def observe(self, name, t, value):
        """Append one point to series `name` at caller-supplied t."""
        self.observe_many(t, ((name, value),))

    def observe_many(self, t, pairs):
        """Append a batch of (name, value) points at one t — the
        engine's per-iteration hot path uses this so the metric inc
        and lock round-trip are paid once per iteration, not once per
        point."""
        n = dropped = 0
        with self._lock:
            for name, value in pairs:
                ring = self._ring(name)
                if ring is None:
                    continue
                before = ring.dropped
                ring.append(t, value)
                dropped += ring.dropped - before
                n += 1
            self._points_total += n
        if n:
            self._m_points.inc(n)
        if dropped:
            self._m_dropped.inc(dropped)

    # -- registry sampling --------------------------------------------------
    def _wants(self, name):
        return any(name.startswith(p) for p in self.prefixes)

    def sample(self, t, registry=None):
        """One sampling tick over a MetricsRegistry: gauges become
        points, counters become rate points (delta/dt vs the previous
        tick, key suffixed ``:rate``; the first tick only establishes
        the baseline). Returns the number of points recorded."""
        reg = registry if registry is not None else global_registry()
        pairs = []
        for name in reg.names():
            if not self._wants(name):
                continue
            metric = reg.get(name)
            kind = getattr(metric, "kind", None)
            if kind == "gauge":
                for labels, child in metric.series():
                    pairs.append((series_key(name, labels),
                                  child.value()))
            elif kind == "counter":
                key = f"{name}:rate"
                total = metric.value()
                prev = self._prev.get(key)
                self._prev[key] = (t, total)
                if prev is not None and t > prev[0]:
                    rate = (total - prev[1]) / (t - prev[0])
                    pairs.append((key, rate))
        self.observe_many(t, pairs)
        return len(pairs)

    # -- read side ----------------------------------------------------------
    def series(self, name):
        """[(t, value), ...] for one series (empty when absent)."""
        with self._lock:
            ring = self._rings.get(name)
            return list(ring.points) if ring is not None else []

    def latest(self, name):
        """The newest (t, value) of a series, or None."""
        with self._lock:
            ring = self._rings.get(name)
            if ring is None or not ring.points:
                return None
            return ring.points[-1]

    def names(self):
        with self._lock:
            return sorted(self._rings)

    def payload(self):
        """The /series body: the empty_series shape, filled in."""
        with self._lock:
            series = {k: {"points": [[t, v] for t, v in r.points],
                          "dropped": r.dropped}
                      for k, r in sorted(self._rings.items())}
            return dict(empty_series(), label=self.label,
                        capacity=self.capacity,
                        points=self._points_total,
                        dropped_points=sum(r.dropped for r in
                                           self._rings.values()),
                        dropped_series=self._dropped_series,
                        series=series)

    to_dict = payload

    @classmethod
    def from_dict(cls, d):
        s = cls(capacity=max(int(d.get("capacity", 1)), 1),
                label=d.get("label"))
        for key, ser in d.get("series", {}).items():
            ring = _Ring(s.capacity)
            ring.dropped = int(ser.get("dropped", 0))
            for t, v in ser.get("points", ()):
                ring.points.append((t, v))
            s._rings[key] = ring
        s._points_total = int(d.get("points", 0))
        s._dropped_series = int(d.get("dropped_series", 0))
        return s

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.payload(), f, separators=(",", ":"),
                      sort_keys=True)
            f.write("\n")
        return path


class FleetSeriesStore:
    """The router's series plane: its own fleet-level store plus a
    reference to each replica's engine-level store, merged with the
    fleet_trace death-snapshot idiom — a killed replica's history is
    frozen into a bounded postmortem ring before the slot is
    resurrected with a fresh store, so ``merged()`` never loses the
    victim's half of a storm."""

    #: bounded postmortem snapshots of dead replicas' series
    MAX_SNAPSHOTS = 16

    def __init__(self, name, capacity=512, max_series=256):
        self.name = name
        self.fleet = SeriesStore(capacity=capacity,
                                 max_series=max_series,
                                 label=f"fleet router {name}")
        self._live = {}              # replica name -> (generation, store)
        self._dead = collections.deque(maxlen=self.MAX_SNAPSHOTS)
        self._snapshots_dropped = 0
        self._lock = threading.Lock()

    def attach(self, name, store, generation=0):
        """Register replica slot `name`'s live store at `generation`.
        A resurrection re-registers the slot name with the fresh
        engine's store — the old history must already be snapshotted
        (snapshot_replica) or it is snapshotted here."""
        with self._lock:
            old = self._live.get(name)
            if old is not None and old[1] is store:
                return
            if old is not None:
                self._snapshot_locked(name)
            self._live[name] = (int(generation), store)

    def snapshot_replica(self, name):
        """Freeze a dying replica's series into the postmortem ring
        (idempotent per registration), mirroring
        FleetTracer.snapshot_replica."""
        with self._lock:
            self._snapshot_locked(name)

    def _snapshot_locked(self, name):
        entry = self._live.pop(name, None)
        if entry is None:
            return
        gen, store = entry
        if len(self._dead) == self._dead.maxlen:
            self._snapshots_dropped += 1
        self._dead.append((f"replica {name} gen{gen} (dead)",
                           store.payload()))

    def merged(self):
        """ONE payload over every source: the fleet store, each dead
        replica's snapshot, and each live replica's store. A dropped
        snapshot marks the payload truncated — a partial history must
        never read as a complete one."""
        with self._lock:
            sources = [(f"fleet router {self.name}",
                        self.fleet.payload())]
            sources.extend(self._dead)
            for name in sorted(self._live):
                gen, store = self._live[name]
                label = (f"replica {name}" if gen == 0
                         else f"replica {name} gen{gen}")
                sources.append((label, store.payload()))
            snapshots_dropped = self._snapshots_dropped
        return {"schema": FLEET_SCHEMA, "router": self.name,
                "sources": [{"name": label, **payload}
                            for label, payload in sources],
                "snapshots_dropped": snapshots_dropped,
                "truncated": snapshots_dropped > 0}

    def save(self, path, payload=None):
        payload = payload if payload is not None else self.merged()
        with open(path, "w") as f:
            json.dump(payload, f, separators=(",", ":"),
                      sort_keys=True)
            f.write("\n")
        return path

    def stats(self):
        with self._lock:
            return {"live_stores": len(self._live),
                    "dead_snapshots": len(self._dead),
                    "snapshots_dropped": self._snapshots_dropped,
                    "fleet_points": self.fleet._points_total}
