"""Fleet-wide distributed tracing: one trace id per request across
routing, handoff, failover, and resurrection.

PR 7's span trees and flight recorders stop at the replica boundary:
a request that is routed, shed, handed off, failed over, or
quarantined has its story scattered across N replicas' telemetry with
no shared correlation key — debugging the PR 12 storm means manually
joining flight dumps by rid. This module is the correlation layer the
fleet router (serving/router.py) threads through every hop:

- **TraceContext** — the router mints ONE deterministic trace id per
  request at submit (blake2b of router name + router rid: no clocks,
  no RNG — injected-clock-safe and replay-stable) plus a hop counter
  that increments on every replica submission (first routing,
  disaggregated prefill→decode handoff, each failover re-admission).
  The context rides the router's ``_Routed`` record and is passed to
  ``GenerationServer.submit(trace_ctx=...)``, so every replica's
  ServingTelemetry span tree carries ``trace_id``/``hop`` in its args.
  The SAMPLING decision travels IN the context: the router evaluates
  ``PADDLE_TPU_TRACE_REQUESTS`` once per request, and every hop obeys
  that one verdict — engines never re-hash their replica-local rid
  (which changes on failover and would desync hops).

- **FleetTracer** — one TraceRecorder per replica slot (so the merged
  Perfetto view renders per-replica PROCESS groups) plus a dedicated
  fleet recorder for router-level events: route decisions (policy,
  affinity depth, candidate loads), SLO sheds (burn rate,
  retry-after), KV handoffs (chunks/blocks/bytes moved), failover
  re-admissions (cause, source→target), and supervisor lifecycle
  events (kill, hang, resurrection, crash loop, quarantine) as
  instants on the ``fleet router`` track. All recorders share one
  perf_counter origin, so cross-replica stamps are directly
  comparable — a failed-over request's hop-1 spans land strictly
  after its hop-0 spans in the merged timeline.

- **Death snapshots** — when a replica dies (kill, hang teardown,
  engine fault) its capture is snapshotted before the slot is
  resurrected with a fresh recorder, so the victim's half of a
  failover survives into the postmortem dump. Snapshots are a bounded
  ring; dropping one marks the merged dump truncated.

- **Completed-trace ring** — a bounded drop-oldest ring of per-request
  summaries (trace id, hops, lineage, outcome) served by the
  ``/trace`` exporter endpoint and consumed by
  ``tools/request_trace.py`` to reconstruct one rid's end-to-end
  timeline.

``FleetRouter.dump_trace()`` merges everything into ONE Perfetto JSON
(``merge()`` here): each source becomes its own pid with a
``process_name`` metadata row, and ``otherData`` carries per-source
dropped-event counts plus a ``truncated`` flag so a partial capture is
never mistaken for a complete one. Metrics:
``serving.fleet.trace.{requests,completed,dumps}``
(docs/observability.md "Fleet tracing").
"""

import collections
import hashlib
import json
import threading
import time

from .tracing import TraceRecorder

__all__ = ["TraceContext", "FleetTracer", "mint_trace_id",
           "empty_trace_ring"]


def empty_trace_ring():
    """The ``paddle_tpu.trace_ring/1`` payload with no trace plane
    behind it — the /trace body a component WITHOUT a fleet tracer
    serves (exporter.py). One definition for the schema's empty shape:
    ``FleetTracer.completed_payload()`` builds on it, so a field added
    there automatically appears here and the two producers of the
    declared schema can never diverge."""
    return {"schema": "paddle_tpu.trace_ring/1", "router": None,
            "capacity": 0, "recorded": 0, "truncated": False,
            "traces": []}


def mint_trace_id(router_ident, rid):
    """Deterministic 16-hex-char trace id for one router request.

    blake2b over "<router identity>:<router rid>" — no wall clock, no
    RNG (the injected-clock serving tier must mint the same id on
    every replay). `router_ident` must be process-unique: auto-named
    routers pass their name (fleet<N>); explicitly-named routers pass
    a per-instance disambiguated form (router._trace_ident), because
    two routers sharing one explicit name would otherwise mint
    identical ids for unrelated requests."""
    return hashlib.blake2b(f"{router_ident}:{int(rid)}".encode(),
                           digest_size=8).hexdigest()


class TraceContext:
    """One request's fleet trace coordinates: the router-minted trace
    id, the hop this submission is (0 = first routing; +1 per
    handoff/failover re-admission), and the router's ONE sampling
    verdict — a replica given a context must not re-decide sampling
    from its replica-local rid (which changes on failover and would
    trace some hops of a request but not others)."""

    __slots__ = ("trace_id", "hop", "sampled")

    def __init__(self, trace_id, hop=0, sampled=True):
        self.trace_id = trace_id
        self.hop = int(hop)
        self.sampled = bool(sampled)

    def at(self, hop):
        """The same trace at a later hop (contexts are immutable —
        every replica submission gets its own)."""
        return TraceContext(self.trace_id, hop, self.sampled)

    def args(self):
        """The correlation args every span/instant of this trace
        carries."""
        return {"trace_id": self.trace_id, "hop": self.hop}

    def __repr__(self):
        return (f"TraceContext({self.trace_id}, hop={self.hop}, "
                f"sampled={self.sampled})")


class FleetTracer:
    """The router's trace plane: a fleet recorder (router-level track)
    plus one TraceRecorder per replica slot, all sharing one
    perf_counter origin; death snapshots; the completed-trace ring.

    start()/stop() gate capture exactly like the global recorder —
    everything except the completed ring is a no-op while stopped, so
    a tracing-off fleet pays only the per-submit context mint."""

    #: bounded postmortem snapshots of dead replicas' captures
    MAX_SNAPSHOTS = 16

    def __init__(self, name, max_events=None, completed_capacity=256):
        self.name = name
        self._max_events = max_events
        self.fleet = TraceRecorder(max_events=max_events)
        self._live = {}             # replica name -> (generation, rec)
        self._dead = collections.deque(maxlen=self.MAX_SNAPSHOTS)
        self._snapshots_dropped = 0
        self._completed = collections.deque(maxlen=completed_capacity)
        self._completed_total = 0
        self._lock = threading.Lock()
        self._origin = None
        self._epoch0 = None

    # -- lifecycle ----------------------------------------------------------
    @property
    def enabled(self):
        return self.fleet.enabled

    def start(self):
        """Begin a fleet-wide capture: every recorder (fleet + all live
        replicas) starts against ONE shared perf_counter origin, so
        stamps merge into a single comparable timeline."""
        with self._lock:
            self._origin = time.perf_counter()
            self._epoch0 = time.time()
            self.fleet.start(origin=self._origin)
            for _gen, rec in self._live.values():
                rec.start(origin=self._origin)
            self._dead.clear()
            self._snapshots_dropped = 0

    def stop(self):
        with self._lock:
            self.fleet.stop()
            for _gen, rec in self._live.values():
                rec.stop()

    # -- replica recorders --------------------------------------------------
    def recorder_for(self, name, generation=0):
        """The (fresh) recorder for replica slot `name` at
        `generation`. A resurrection re-registers the slot name with a
        new recorder — the old capture must already be snapshotted
        (snapshot_replica) or it is snapshotted here."""
        with self._lock:
            old = self._live.get(name)
            if old is not None and old[0] == generation:
                return old[1]
            if old is not None:
                self._snapshot_locked(name)
            rec = TraceRecorder(max_events=self._max_events)
            if self.fleet.enabled:
                rec.start(origin=self._origin)
            self._live[name] = (int(generation), rec)
            return rec

    def snapshot_replica(self, name):
        """Freeze a dying replica's capture into the postmortem ring
        (idempotent per registration): the victim's half of a failover
        must survive the slot's resurrection, which swaps in a fresh
        recorder under the same name."""
        with self._lock:
            self._snapshot_locked(name)

    def _snapshot_locked(self, name):
        entry = self._live.pop(name, None)
        if entry is None:
            return
        gen, rec = entry
        if len(self._dead) == self._dead.maxlen:
            self._snapshots_dropped += 1
        rec.stop()
        self._dead.append((f"replica {name} gen{gen} (dead)",
                           rec.to_chrome()))

    # -- completed-trace ring -----------------------------------------------
    def note_completed(self, record):
        """Append one finished request's trace summary (served by the
        /trace endpoint; host bookkeeping, live even while the span
        capture is stopped)."""
        with self._lock:
            self._completed.append(record)
            self._completed_total += 1

    def completed_payload(self):
        """The /trace endpoint body: newest-last bounded ring (the
        empty_trace_ring shape, filled in)."""
        with self._lock:
            return dict(empty_trace_ring(),
                        router=self.name,
                        capacity=self._completed.maxlen,
                        recorded=self._completed_total,
                        truncated=self._completed_total
                        > len(self._completed),
                        traces=list(self._completed))

    # -- merge --------------------------------------------------------------
    def merge(self):
        """ONE Perfetto JSON over every capture: the fleet track, each
        dead replica's snapshot, and each live replica's recorder —
        one pid per source with a process_name metadata row, so
        Perfetto renders per-replica process groups. otherData carries
        per-source dropped counts and a `truncated` flag (any ring
        drop anywhere means the dump is partial)."""
        with self._lock:
            sources = [(f"fleet router {self.name}",
                        self.fleet.to_chrome())]
            sources.extend(self._dead)
            for name in sorted(self._live):
                gen, rec = self._live[name]
                label = (f"replica {name}" if gen == 0
                         else f"replica {name} gen{gen}")
                sources.append((label, rec.to_chrome()))
            snapshots_dropped = self._snapshots_dropped
            epoch0 = self._epoch0
        events, source_meta = [], []
        for pid, (label, chrome) in enumerate(sources):
            dropped = chrome.get("otherData", {}).get(
                "dropped_events", 0)
            n = 0
            for e in chrome.get("traceEvents", ()):
                e = dict(e)
                e["pid"] = pid
                if e.get("ph") == "M" and e.get("name") == \
                        "process_name":
                    e["args"] = {"name": label}
                else:
                    n += 1 if e.get("ph") != "M" else 0
                events.append(e)
            source_meta.append({"name": label, "pid": pid,
                                "events": n,
                                "dropped_events": dropped})
        truncated = snapshots_dropped > 0 or any(
            s["dropped_events"] for s in source_meta)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {
                    "schema": "paddle_tpu.fleet_trace/1",
                    "router": self.name,
                    "start_epoch_s": epoch0,
                    "sources": source_meta,
                    "snapshots_dropped": snapshots_dropped,
                    "truncated": truncated,
                }}

    def save(self, path, payload=None):
        payload = payload if payload is not None else self.merge()
        with open(path, "w") as f:
            json.dump(payload, f, separators=(",", ":"))
            f.write("\n")
        return path

    def stats(self):
        with self._lock:
            return {"enabled": self.fleet.enabled,
                    "replica_recorders": len(self._live),
                    "dead_snapshots": len(self._dead),
                    "snapshots_dropped": self._snapshots_dropped,
                    "completed": len(self._completed),
                    "completed_total": self._completed_total,
                    "completed_capacity": self._completed.maxlen}
