"""Autoregressive decoding with a KV cache — greedy and beam search.

Parity: the reference decodes with a While block doing LoD beam surgery
per step (layers/beam_search + tests/book machine translation). TPU-native:
the whole decode is ONE `lax.scan` over time with static shapes — dense
(batch, beam) lanes, finished lanes masked, KV cache updated functionally
via dynamic_update_slice. No host round-trips inside the loop.

The model plugs in as `step_fn(ids_t, cache, t) -> (logits, cache)`:
- ids_t: (B,) or (B*K,) current token ids
- cache: arbitrary pytree (e.g. per-layer K/V of shape (B, H, T_max, D))
- logits: (B, V) next-token logits
Helpers `init_kv_cache` / `update_kv_cache` build that cache the standard
way so model code stays three lines.
"""


import jax
import jax.numpy as jnp

NEG_INF = -1e9


# ---------------------------------------------------------------------------
# KV cache helpers
# ---------------------------------------------------------------------------

def init_kv_cache(batch, num_layers, num_heads, max_len, head_dim,
                  dtype=jnp.float32):
    """Pytree: list of {'k','v'} with shape (B, H, T_max, D)."""
    shape = (batch, num_heads, max_len, head_dim)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(num_layers)]


def update_kv_cache(layer_cache, k_t, v_t, t):
    """Write this step's K/V (B, H, 1, D) at time t. Returns new cache +
    full (B, H, T_max, D) views for attention (mask out > t). The cache
    dtype wins: a bf16 serving cache accepts K/V computed through f32
    residual paths without the caller micro-managing casts.

    Layer caches exposing `paged_update` (serving.kv_cache's
    PagedDecodeLayer) route through it instead: the same step_fn then
    decodes against a block-pooled paged cache unchanged — the adapter
    presents the gathered dense view via the same {'k','v'} mapping
    interface."""
    if hasattr(layer_cache, "paged_update"):
        return layer_cache.paged_update(k_t, v_t, t)
    k = jax.lax.dynamic_update_slice(
        layer_cache["k"], k_t.astype(layer_cache["k"].dtype), (0, 0, t, 0))
    v = jax.lax.dynamic_update_slice(
        layer_cache["v"], v_t.astype(layer_cache["v"].dtype), (0, 0, t, 0))
    return {"k": k, "v": v}


def cache_attention_bias(max_len, t):
    """(1, 1, 1, T_max) additive bias masking positions > t."""
    pos = jnp.arange(max_len)
    return jnp.where(pos <= t, 0.0, NEG_INF)[None, None, None, :]


# ---------------------------------------------------------------------------
# Greedy
# ---------------------------------------------------------------------------

def greedy_decode(step_fn, init_cache, bos_ids, max_len, eos_id=None,
                  start_t=0):
    """Returns (ids (B, max_len), scores (B,)). Stops contributing after
    EOS (lanes keep stepping — static shapes — but emit eos/score 0).
    `start_t` begins the scan at a later position — the continuation
    path after a parallel prompt prefill has filled cache[..., :start_t]
    (models/gpt.py build_prefill / generate_with_prompt); max_len then counts GENERATED
    steps, not absolute positions."""
    batch = bos_ids.shape[0]

    def body(carry, t):
        ids_t, cache, done, score = carry
        logits, cache = step_fn(ids_t, cache, t)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nxt = jnp.argmax(logp, axis=-1)
        step_lp = jnp.take_along_axis(logp, nxt[:, None], -1)[:, 0]
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            score = score + jnp.where(done, 0.0, step_lp)
            done = done | (nxt == eos_id)
        else:
            score = score + step_lp
        return (nxt, cache, done, score), nxt

    carry0 = (bos_ids, init_cache, jnp.zeros(batch, bool),
              jnp.zeros(batch, jnp.float32))
    (_, _, _, scores), ids = jax.lax.scan(body, carry0,
                                          jnp.arange(max_len) + start_t)
    return ids.T, scores


def _filter_logits(logits, top_k=None, top_p=None):
    """Standard sampling filters over (B, V) f32 logits: keep the top_k
    highest, then the smallest prefix of the sorted distribution whose
    cumulative probability reaches top_p (the nucleus); everything else
    -> NEG_INF. Static shapes throughout (TPU-compilable)."""
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep ranks whose PRECEDING mass is < top_p (always >= 1 token)
        keep_sorted = jnp.concatenate(
            [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < top_p], -1)
        # threshold logit: the smallest kept logit per row
        thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, NEG_INF, logits)
    return logits


def sample_decode(step_fn, init_cache, bos_ids, max_len, rng_key,
                  temperature=1.0, top_k=None, top_p=None, eos_id=None,
                  start_t=0):
    """Stochastic decoding with a KV cache: temperature scaling, then
    top-k and/or nucleus (top-p) filtering, then categorical sampling —
    the serving-side complement of greedy_decode (same carry/eos/start_t
    conventions; parity root: the reference's sampling_id op, here
    composed with the cache loop). temperature <= 0 degenerates to
    greedy argmax. Returns (ids (B, max_len), scores (B,)) where score
    sums the chosen tokens' log-probs under the FILTERED distribution."""
    batch = bos_ids.shape[0]
    greedy = temperature is None or temperature <= 0.0

    def body(carry, t):
        ids_t, cache, done, score, key = carry
        logits, cache = step_fn(ids_t, cache, t)
        logits = logits.astype(jnp.float32)
        if greedy:
            filtered = logits
            nxt = jnp.argmax(filtered, axis=-1)
        else:
            filtered = _filter_logits(logits / temperature,
                                      top_k=top_k, top_p=top_p)
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, filtered, axis=-1)
        logp = jax.nn.log_softmax(filtered)
        step_lp = jnp.take_along_axis(logp, nxt[:, None], -1)[:, 0]
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            score = score + jnp.where(done, 0.0, step_lp)
            done = done | (nxt == eos_id)
        else:
            score = score + step_lp
        return (nxt, cache, done, score, key), nxt

    carry0 = (bos_ids, init_cache, jnp.zeros(batch, bool),
              jnp.zeros(batch, jnp.float32), rng_key)
    (_, _, _, scores, _), ids = jax.lax.scan(
        body, carry0, jnp.arange(max_len) + start_t)
    return ids.T, scores


# ---------------------------------------------------------------------------
# Beam search
# ---------------------------------------------------------------------------

def _gather_beams(tree, parent, batch, beams):
    """Reorder the (B*K, ...) leading dim by parent beam indices (B, K)."""
    flat_idx = (jnp.arange(batch)[:, None] * beams + parent).reshape(-1)
    return jax.tree_util.tree_map(lambda x: x[flat_idx], tree)


def beam_decode(step_fn, init_cache, bos_ids, max_len, beam_size, eos_id,
                length_penalty=0.6, start_t=0):
    """Standard beam search, dense lanes, GNMT length penalty.

    init_cache leaves must already be (B*K, ...) — tile with
    `jax.tree_util.tree_map(lambda x: jnp.repeat(x, K, 0), cache)`.
    bos_ids: (B,). Returns (ids (B, K, max_len), scores (B, K)) sorted
    best-first. `start_t` begins the scan at a later position — the
    prompt-conditioned path feeds the prompt's LAST token with a
    prefilled cache and start_t = P - 1 (the step re-writes that
    position's K/V with identical values and emits position P's
    token); max_len then counts GENERATED steps."""
    batch = bos_ids.shape[0]
    K = beam_size

    ids0 = jnp.repeat(bos_ids, K)                       # (B*K,)
    # lane 0 active, others -inf so step 1 doesn't duplicate beams
    scores0 = jnp.tile(jnp.array([0.0] + [NEG_INF] * (K - 1),
                                 jnp.float32), (batch,))
    done0 = jnp.zeros(batch * K, bool)

    def body(carry, t):
        ids_t, cache, done, scores = carry
        logits, cache = step_fn(ids_t, cache, t)        # (B*K, V)
        vocab = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        # finished lanes may only emit EOS at zero cost
        eos_only = jnp.full((vocab,), NEG_INF).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None, :], logp)

        total = scores[:, None] + logp                  # (B*K, V)
        total = total.reshape(batch, K * vocab)
        top_scores, top_idx = jax.lax.top_k(total, K)   # (B, K)
        parent = top_idx // vocab
        token = (top_idx % vocab).astype(ids_t.dtype)

        cache = _gather_beams(cache, parent, batch, K)
        done = _gather_beams(done, parent, batch, K)
        done = done | (token.reshape(-1) == eos_id)
        return ((token.reshape(-1), cache, done,
                 top_scores.reshape(-1)),
                (token, parent))

    carry0 = (ids0, init_cache, done0, scores0)
    (_, _, _, final_scores), (tokens, parents) = jax.lax.scan(
        body, carry0, jnp.arange(max_len) + start_t)
    # tokens/parents: (T, B, K). Backtrack parent pointers into sequences.

    def backtrack(carry, xs):
        beam_idx = carry                                 # (B, K)
        token_t, parent_t = xs
        tok = jnp.take_along_axis(token_t, beam_idx, axis=1)
        beam_idx = jnp.take_along_axis(parent_t, beam_idx, axis=1)
        return beam_idx, tok

    last = jnp.tile(jnp.arange(K)[None, :], (batch, 1))
    _, seq_rev = jax.lax.scan(backtrack, last, (tokens, parents),
                              reverse=True)
    ids = jnp.moveaxis(seq_rev, 0, 2)                    # (B, K, T)

    lengths = jnp.sum(ids != eos_id, axis=-1).astype(jnp.float32) + 1.0
    lp = ((5.0 + lengths) / 6.0) ** length_penalty
    final = final_scores.reshape(batch, K) / lp
    order = jnp.argsort(-final, axis=1)
    ids = jnp.take_along_axis(ids, order[:, :, None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return ids, final
