"""Predictor: load_inference_model -> AOT-compiled callable.

Parity: paddle/fluid/inference/api/analysis_predictor.cc. The reference
builds an executor over an optimized graph and keeps zero-copy input/output
tensors. TPU-native mapping:
- graph optimization  -> XLA (jit once per input signature, cached);
- zero-copy tensors   -> jax device_put + donated buffers on request;
- warmup              -> `warmup()` pre-compiles signatures (AOT lower+compile);
- batch server loop   -> `predict_batch` slices/pads to the compiled batch.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..core.executor import Executor, Scope, scope_guard
from ..io.inference_io import load_inference_model


class AnalysisConfig:
    """Parity: AnalysisConfig — the knobs that matter on TPU."""

    def __init__(self, model_dir=None):
        self.model_dir = model_dir
        self.use_bf16 = False
        self.fixed_batch_sizes = ()   # pad-to-bucket batch sizes
        self.donate_inputs = False
        self.mesh = None              # tensor-parallel serving mesh
        self.shard_rules = None
        self.generation = None        # enable_generation() options
        self.use_int8 = False
        self.int8_calibration_feeds = None

    def enable_bf16(self):
        self.use_bf16 = True
        return self

    def enable_int8(self, calibration_feeds=None):
        """Quantized serving, end to end (the TPU successor to Fluid's
        `quant/`+`slim/` PTQ surfaces — see MIGRATION.md):

        - weight side: every quantizable matmul/conv weight in the
          loaded program gets per-output-channel absmax int8
          quant-dequant (quant/ptq.apply_ptq with
          weight_granularity="channel"); pass `calibration_feeds`
          (an iterable of feed dicts, a few hundred samples) to also
          calibrate static activation scales the reference-PTQ way
          (quant/ptq.calibrate_program). Data-free weight-only quant
          is the default — absmax needs nothing but the weights.
        - generation side: `generation_server()` folds per-channel
          int8 weights into the fused step
          (GPTServingModel.quantize_int8 — dequant inline, signature
          budget unchanged) and defaults the KV pool to
          kv_dtype="int8" (int8 blocks + f32 scales; override by
          passing kv_dtype explicitly to enable_generation /
          generation_server).

        Accuracy deltas are pinned in tier-1
        (tests/api/test_quant_serving.py); docs/serving.md "Quantized
        serving" covers when NOT to quantize."""
        self.use_int8 = True
        self.int8_calibration_feeds = calibration_feeds
        return self

    def enable_generation(self, gpt_config, **server_opts):
        """Serve this model as a continuous-batching generation engine
        (paddle_tpu.serving.GenerationServer over a paged KV cache).
        `gpt_config` names the decoder architecture the exported
        gpt_* parameters follow (models/gpt.GPTConfig); `server_opts`
        pass through to GenerationServer (num_slots, block_size,
        max_context, chunk, watermark_blocks, ...). The server itself
        is built lazily by `Predictor.generation_server()`."""
        self.generation = dict(server_opts)
        self.generation["gpt_config"] = gpt_config
        return self

    def set_batch_buckets(self, sizes):
        self.fixed_batch_sizes = tuple(sorted(sizes))
        return self

    def enable_tensor_parallel(self, mesh, rules=None):
        """Serve the model sharded over `mesh`'s tp axis: params get
        dist_attr annotations (parallel/tensor_parallel.ShardRules —
        pass `rules` to customize) and the forward runs as one GSPMD-
        partitioned executable, XLA inserting the tp collectives. The
        multi-chip analogue of the reference's multi-stream serving."""
        self.mesh = mesh
        self.shard_rules = rules
        return self


class Predictor:
    def __init__(self, config):
        import os
        self.config = config
        self.scope = Scope()
        self._exe = Executor()
        with scope_guard(self.scope):
            if os.path.exists(os.path.join(config.model_dir, "__model__")):
                # a REFERENCE export dir (save_inference_model's ProgramDesc
                # protobuf + weights): serve it directly (io/fluid_proto.py)
                from ..io.fluid_proto import load_fluid_inference_model
                (self.program, self.feed_names,
                 self.fetch_names) = load_fluid_inference_model(
                    config.model_dir, self._exe)
            else:
                (self.program, self.feed_names,
                 fetch_vars) = load_inference_model(config.model_dir,
                                                    self._exe)
                self.fetch_names = [v.name for v in fetch_vars]
        if config.use_bf16:
            self._cast_params_bf16()
        if config.use_int8:
            self._apply_int8()
        # tensor-parallel serving: annotate params + attach the mesh so
        # the Executor's pjit path shards state and partitions the step.
        # Annotate every persistable VAR (not Parameter objects): the
        # reference-__model__ protobuf branch above rebuilds weights as
        # plain Variables, which program.all_parameters() misses — that
        # branch would otherwise serve silently replicated.
        self._run_prog = self.program
        if config.mesh is not None:
            from ..core.compiler import CompiledProgram
            from ..parallel.tensor_parallel import ShardRules
            rules = config.shard_rules or ShardRules()
            annotated = 0
            for v in self.program.list_vars():
                if v.persistable and v.name not in ("feed", "fetch"):
                    v.dist_attr = rules.spec_for(v.name, v.shape)
                    if tuple(v.dist_attr or ()):
                        annotated += 1
            if annotated == 0:
                import warnings
                warnings.warn(
                    "enable_tensor_parallel: no parameter matched the "
                    "shard rules (param naming may not follow the "
                    "attn_*/ffn* conventions) — serving will run "
                    "REPLICATED; pass AnalysisConfig.shard_rules with "
                    "patterns for this model's names")
            self._run_prog = CompiledProgram(self.program).with_mesh(
                config.mesh)

    def _cast_params_bf16(self):
        # Param tensors move to bf16; XLA keeps matmuls on the MXU in bf16.
        for name in list(self.scope.names()):
            v = self.scope.get(name)
            if v is not None and jnp.issubdtype(
                    jnp.asarray(v).dtype, jnp.floating):
                self.scope.set(name, jnp.asarray(v, jnp.bfloat16))

    def _apply_int8(self):
        """The enable_int8 program rewrite: optional activation
        calibration over the configured feeds, then per-output-channel
        weight quant-dequant on every quantizable op (quant/ptq.py —
        the machinery Fluid's slim PTQ used, routed at the TPU path).
        Parameters only: the reference-__model__ protobuf branch
        rebuilds weights as plain Variables, which apply_ptq skips —
        that branch serves fp until re-exported with Parameters."""
        from ..observability import _help
        from ..observability.metrics import global_registry
        from ..quant.ptq import apply_ptq, calibrate_program
        scales = {}
        feeds = self.config.int8_calibration_feeds
        if feeds:
            with scope_guard(self.scope):
                scales = calibrate_program(self._exe, self.program,
                                           feeds)
        apply_ptq(self.program, scales, weight_granularity="channel")
        ops = self.program.global_block().ops
        self.int8_weight_tensors = sum(
            1 for op in ops
            if op.type in ("fake_quantize_dequantize_abs_max",
                           "fake_channel_wise_quantize_dequantize_"
                           "abs_max"))
        self.int8_calibrated_activations = sum(
            1 for op in ops
            if op.type == "quantize_dequantize_static_scale")
        reg = global_registry()
        reg.counter("inference.int8.weights",
                    _help("inference.int8.weights")).inc(
                        self.int8_weight_tensors)
        reg.counter(
            "inference.int8.calibrated_activations",
            _help("inference.int8.calibrated_activations")).inc(
                self.int8_calibrated_activations)

    # -- the reference's ZeroCopyRun / run APIs ---------------------------
    def run(self, feeds):
        """feeds: {name: array}. Returns list of np arrays (fetch order)."""
        if isinstance(feeds, (list, tuple)):
            feeds = dict(zip(self.feed_names, feeds))
        if self.config.use_bf16:
            feeds = {k: (np.asarray(v, np.float32)
                         if np.asarray(v).dtype.kind == "f" else v)
                     for k, v in feeds.items()}
        with scope_guard(self.scope):
            return self._exe.run(self._run_prog, feed=feeds,
                                 fetch_list=self.fetch_names)

    __call__ = run

    def predict_batch(self, feeds):
        """Bucket-padded batch path: pad batch dim up to the nearest
        compiled bucket so every request hits a cached executable."""
        if not self.config.fixed_batch_sizes:
            return self.run(feeds)
        if isinstance(feeds, (list, tuple)):
            feeds = dict(zip(self.feed_names, feeds))
        n = next(iter(feeds.values())).shape[0]
        largest = self.config.fixed_batch_sizes[-1]
        if n > largest:
            # Split oversized requests into largest-bucket chunks so the
            # serving path never sees an uncompiled input signature.
            chunks = []
            for s in range(0, n, largest):
                chunks.append(self.predict_batch(
                    {k: np.asarray(v)[s:s + largest]
                     for k, v in feeds.items()}))
            return [np.concatenate(parts) for parts in zip(*chunks)]
        bucket = next(b for b in self.config.fixed_batch_sizes if b >= n)
        padded = {k: np.concatenate(
            [np.asarray(v)] + [np.zeros_like(np.asarray(v)[:1])] * (bucket - n))
            if bucket > n else np.asarray(v) for k, v in feeds.items()}
        outs = self.run(padded)
        return [o[:n] for o in outs]

    def warmup(self, example_feeds_list):
        """AOT pre-compile every expected signature (parity: the reference's
        warmup passes). First compile is the slow step on TPU; do it here,
        not on the serving path."""
        for feeds in example_feeds_list:
            self.run(feeds)
        return self

    def generation_server(self, **overrides):
        """Build a paddle_tpu.serving.GenerationServer over this
        predictor's loaded parameters (requires
        AnalysisConfig.enable_generation). The loaded scope must hold
        models/gpt.py's gpt_* parameter names — i.e. the export came
        from a GPT-family program. bf16 serving (enable_bf16) casts
        the KV pool and activations to bf16."""
        if self.config.generation is None:
            raise RuntimeError(
                "generation not enabled: call "
                "AnalysisConfig.enable_generation(gpt_config, ...) "
                "before create_predictor")
        from ..serving import GenerationServer, GPTServingModel
        opts = dict(self.config.generation)
        opts.update(overrides)
        gpt_cfg = opts.pop("gpt_config")
        dtype = jnp.bfloat16 if self.config.use_bf16 else None
        model = GPTServingModel.from_scope(self.scope, gpt_cfg,
                                           dtype=dtype)
        if self.config.use_int8:
            # enable_int8 means quantized SERVING end to end: int8
            # weights folded into the fused step AND int8 KV blocks
            # (kv_dtype stays overridable for the weights-only case)
            model.quantize_int8()
            opts.setdefault("kv_dtype", "int8")
        return GenerationServer(model, **opts)

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def create_predictor(config_or_dir):
    if isinstance(config_or_dir, str):
        config_or_dir = AnalysisConfig(config_or_dir)
    return Predictor(config_or_dir)
