"""AOT serving artifacts: compile a Program once, serialize, serve anywhere.

Parity: the reference's inference engine ahead-of-time story
(paddle/fluid/inference — an optimized, self-contained artifact the
serving fleet loads without the training stack). TPU-native mapping:
`jax.export` serializes the traced+lowered StableHLO (params baked in as
constants) per feed-shape signature; `load_aot_model` deserializes and
calls it — no Program rebuild, no op registry, no scope at serve time.

    save_aot_model(path, program, feed_names, fetch_names,
                   example_batches=[1, 8], scope=scope)
    model = load_aot_model(path)
    out = model.run({"x": x})          # picks the matching signature
"""

import json
import os

import numpy as np

import jax
import jax.numpy as jnp


def _infer_fn(program, feed_names, fetch_names, state):
    """Pure fn(feeds dict) -> [fetches], closing over the param values
    (they become constants in the exported artifact)."""
    from ..core.executor import _lower_block

    gb = program.global_block()

    def fn(feeds):
        env = dict(state)
        env.update(feeds)
        env["@RNG@"] = jax.random.PRNGKey(0)
        _lower_block(gb, env, program, is_test=True)
        return [env[n] for n in fetch_names]

    return fn


def _feed_specs(program, feed_names, batch):
    specs = {}
    gb = program.global_block()
    for name in feed_names:
        v = gb.var(name)
        dims = [int(d) for d in v.shape]
        if not dims:
            raise ValueError(f"feed '{name}' has no declared shape")
        if dims[0] == -1:
            dims[0] = batch
        elif dims[0] != batch:
            # fluid.data with a STATIC leading batch: the var shape already
            # includes it; a different requested bucket can't exist
            raise ValueError(
                f"feed '{name}' declares a static batch {dims[0]}; "
                f"example_batches must be ({dims[0]},), got {batch}")
        if any(d < 0 for d in dims[1:]):
            raise ValueError(
                f"feed '{name}' has a dynamic non-batch dim {dims}; AOT "
                "export is shape-specialized — declare static shapes")
        specs[name] = jax.ShapeDtypeStruct(tuple(dims),
                                           jnp.dtype(v.dtype))
    return specs


def save_aot_model(dirname, program, feed_names, fetch_names,
                   example_batches=(1,), scope=None):
    """Export one serialized artifact per batch size. The program should
    be an inference graph (clone(for_test=True) / load_inference_model
    output); params come from `scope` (default global)."""
    from ..core.executor import global_scope

    scope = scope or global_scope()
    fetch_names = [v.name if hasattr(v, "name") else v for v in fetch_names]
    state = {}
    gb = program.global_block()
    for v in gb.vars.values():
        if v.persistable and v.name not in ("feed", "fetch"):
            val = scope.get(v.name)
            if val is None:
                raise ValueError(f"param '{v.name}' has no value in scope")
            state[v.name] = jnp.asarray(val)

    fn = _infer_fn(program, feed_names, fetch_names, state)
    specs_by_batch = {int(b): _feed_specs(program, feed_names, b)
                      for b in example_batches}
    return _write_artifacts(dirname, fn, specs_by_batch, feed_names,
                            fetch_names)


def _write_artifacts(dirname, fn, specs_by_batch, feed_names, fetch_names):
    """Serialize fn once per signature + the shared aot_meta.json (the ONE
    place that knows the artifact layout load_aot_model reads)."""
    os.makedirs(dirname, exist_ok=True)
    sigs = []
    for batch, specs in specs_by_batch.items():
        exp = jax.export.export(jax.jit(fn))(specs)
        fname = f"sig_b{batch}.jaxexp"
        with open(os.path.join(dirname, fname), "wb") as f:
            f.write(exp.serialize())
        sigs.append({"batch": batch, "file": fname,
                     "shapes": {k: list(s.shape) for k, s in specs.items()},
                     "dtypes": {k: str(np.dtype(s.dtype))
                                for k, s in specs.items()}})
    meta = {"feed_names": list(feed_names),
            "fetch_names": list(fetch_names), "signatures": sigs}
    with open(os.path.join(dirname, "aot_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return [s["file"] for s in sigs]


def save_aot_callable(dirname, fn, example_feeds, fetch_names=("out",)):
    """Export an arbitrary jittable fn(feeds dict) -> [outputs] as an AOT
    artifact (one signature: the example shapes). Backs dygraph
    TracedLayer.save_inference_model."""
    specs = {}
    for k, v in example_feeds.items():
        arr = jnp.asarray(v)
        specs[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
    first = next(iter(specs.values()))
    batch = int(first.shape[0]) if first.shape else 1
    return _write_artifacts(dirname, fn, {batch: specs}, list(specs),
                            fetch_names)


class AotModel:
    """Serving handle over deserialized signatures; run() dispatches on
    the feed batch size (exact match required — the artifact is
    shape-specialized by design)."""

    def __init__(self, meta, exported):
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]
        self._by_batch = exported          # batch -> jax.export.Exported

    def batch_sizes(self):
        return sorted(self._by_batch)

    def run(self, feeds):
        first = feeds[self.feed_names[0]]
        batch = int(np.asarray(first).shape[0])
        exp = self._by_batch.get(batch)
        if exp is None:
            raise ValueError(
                f"no compiled signature for batch {batch}; available: "
                f"{self.batch_sizes()} (export more via example_batches)")
        args = {k: jnp.asarray(feeds[k]) for k in self.feed_names}
        return [np.asarray(o) for o in exp.call(args)]

    __call__ = run


def load_aot_model(dirname):
    with open(os.path.join(dirname, "aot_meta.json")) as f:
        meta = json.load(f)
    exported = {}
    for sig in meta["signatures"]:
        with open(os.path.join(dirname, sig["file"]), "rb") as f:
            exported[sig["batch"]] = jax.export.deserialize(f.read())
    return AotModel(meta, exported)


# ---------------------------------------------------------------------------
# Train-step export: training without the Python framework
# ---------------------------------------------------------------------------
# Parity: paddle/fluid/train/ (demo/demo_trainer.cc trains a saved
# ProgramDesc from a process with no Python at all — C++ Executor +
# feed/fetch). The TPU-native equivalent exports the WHOLE train step
# (fwd + jax.grad + optimizer update) as one serialized StableHLO
# artifact plus an .npz of the initial state; any process that can
# deserialize jax.export artifacts (python+jax today; the PJRT C API
# for C++ runtimes) trains the model with NO paddle_tpu import, no op
# registry, no Program rebuild — the same "training stack not required
# at the training site" property the reference's standalone trainer
# provides (tests/io/test_train_export.py proves it in a subprocess
# that imports only jax+numpy).

def save_train_step(dirname, program, feed_names, fetch_names,
                    scope=None, batch=1):
    """Export a TRAINING step of `program` (must contain backward +
    optimizer ops, i.e. minimize() was called) as a self-contained
    artifact:

        train_step.jaxexp  — exported fn(state, feeds, rng) ->
                             (new_state, fetches)
        train_state.npz    — initial values of every persistable
                             (params, opt-state, LR counters)
        train_meta.json    — names/shapes/dtypes glue

    The state threads through calls exactly like the Executor's donated
    state pytree, so step semantics (including batch-norm stats and lr
    schedules) match exe.run()."""
    from ..core.executor import Executor, global_scope

    from ..core import framework as _framework

    scope = scope or global_scope()
    fetch_names = [v.name if hasattr(v, "name") else v
                   for v in fetch_names]
    gb = program.global_block()
    persist_names = sorted(
        v.name for v in gb.vars.values()
        if v.persistable and v.name not in ("feed", "fetch"))
    state = {}
    for n in persist_names:
        val = scope.get(n)
        if val is None:
            raise ValueError(f"persistable '{n}' has no value in scope — "
                             f"run the startup program first")
        state[n] = jnp.asarray(val)

    exe = Executor()
    # the export artifact is ALWAYS unguarded: the sentinel would add an
    # extra output to the serialized calling convention, and guarding
    # (PADDLE_TPU_GUARD may be set in this process) belongs to the
    # training loop, not the frozen artifact
    exe._guard = None
    step, _guard_cell = exe._build(
        program, tuple(fetch_names), tuple(persist_names),
        tuple(sorted(state)))
    state_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in state.items()}
    feed_specs = _feed_specs(program, feed_names, batch)
    # (seed, step) pair, same carrier the Executor uses (run():~380)
    rng_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
    exp = jax.export.export(step)(state_specs, feed_specs, rng_spec)

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "train_step.jaxexp"), "wb") as f:
        f.write(exp.serialize())
    np.savez(os.path.join(dirname, "train_state.npz"),
             **{k: np.asarray(v) for k, v in state.items()})
    meta = {
        "feed_names": list(feed_names),
        "fetch_names": fetch_names,
        "state_names": persist_names,
        "batch": int(batch),
        # the Executor derives rng as (program seed, step); record the
        # seed so the artifact's dropout/rng stream matches exe.run()
        "random_seed": int(program.random_seed
                           or _framework.default_seed()),
        "feed_shapes": {k: list(s.shape) for k, s in feed_specs.items()},
        "feed_dtypes": {k: str(np.dtype(s.dtype))
                        for k, s in feed_specs.items()},
    }
    with open(os.path.join(dirname, "train_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return persist_names


class TrainStepArtifact:
    """Standalone trainer handle: state lives here, each run() is one
    optimizer step. Deserializable with ONLY jax+numpy installed."""

    def __init__(self, meta, exported, state):
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]
        self._dtypes = meta.get("feed_dtypes", {})
        self._exp = exported
        self.state = state
        self._step = 0
        self._seed = int(meta.get("random_seed", 0))

    def run(self, feeds):
        arrs = {k: np.asarray(feeds[k]) for k in self.feed_names}
        args = {k: jnp.asarray(a.astype(self._dtypes.get(k, a.dtype)))
                for k, a in arrs.items()}
        rng = jnp.asarray([self._seed & 0xFFFFFFFF,
                           self._step & 0xFFFFFFFF], jnp.uint32)
        self.state, fetches = self._exp.call(self.state, args, rng)
        self._step += 1
        return [np.asarray(f) for f in fetches]

    def save_state(self, path):
        np.savez(path, **{k: np.asarray(v) for k, v in self.state.items()})


def load_train_step(dirname):
    with open(os.path.join(dirname, "train_meta.json")) as f:
        meta = json.load(f)
    with open(os.path.join(dirname, "train_step.jaxexp"), "rb") as f:
        exported = jax.export.deserialize(f.read())
    npz = np.load(os.path.join(dirname, "train_state.npz"))
    state = {k: jnp.asarray(npz[k]) for k in npz.files}
    return TrainStepArtifact(meta, exported, state)
