"""AOT serving artifacts: compile a Program once, serialize, serve anywhere.

Parity: the reference's inference engine ahead-of-time story
(paddle/fluid/inference — an optimized, self-contained artifact the
serving fleet loads without the training stack). TPU-native mapping:
`jax.export` serializes the traced+lowered StableHLO (params baked in as
constants) per feed-shape signature; `load_aot_model` deserializes and
calls it — no Program rebuild, no op registry, no scope at serve time.

    save_aot_model(path, program, feed_names, fetch_names,
                   example_batches=[1, 8], scope=scope)
    model = load_aot_model(path)
    out = model.run({"x": x})          # picks the matching signature
"""

import json
import os

import numpy as np

import jax
import jax.numpy as jnp


def _infer_fn(program, feed_names, fetch_names, state):
    """Pure fn(feeds dict) -> [fetches], closing over the param values
    (they become constants in the exported artifact)."""
    from ..core.executor import _lower_block

    gb = program.global_block()

    def fn(feeds):
        env = dict(state)
        env.update(feeds)
        env["@RNG@"] = jax.random.PRNGKey(0)
        _lower_block(gb, env, program, is_test=True)
        return [env[n] for n in fetch_names]

    return fn


def _feed_specs(program, feed_names, batch):
    specs = {}
    gb = program.global_block()
    for name in feed_names:
        v = gb.var(name)
        dims = [int(d) for d in v.shape]
        if not dims:
            raise ValueError(f"feed '{name}' has no declared shape")
        if dims[0] == -1:
            dims[0] = batch
        elif dims[0] != batch:
            # fluid.data with a STATIC leading batch: the var shape already
            # includes it; a different requested bucket can't exist
            raise ValueError(
                f"feed '{name}' declares a static batch {dims[0]}; "
                f"example_batches must be ({dims[0]},), got {batch}")
        if any(d < 0 for d in dims[1:]):
            raise ValueError(
                f"feed '{name}' has a dynamic non-batch dim {dims}; AOT "
                "export is shape-specialized — declare static shapes")
        specs[name] = jax.ShapeDtypeStruct(tuple(dims),
                                           jnp.dtype(v.dtype))
    return specs


def save_aot_model(dirname, program, feed_names, fetch_names,
                   example_batches=(1,), scope=None):
    """Export one serialized artifact per batch size. The program should
    be an inference graph (clone(for_test=True) / load_inference_model
    output); params come from `scope` (default global)."""
    from ..core.executor import global_scope

    scope = scope or global_scope()
    fetch_names = [v.name if hasattr(v, "name") else v for v in fetch_names]
    state = {}
    gb = program.global_block()
    for v in gb.vars.values():
        if v.persistable and v.name not in ("feed", "fetch"):
            val = scope.get(v.name)
            if val is None:
                raise ValueError(f"param '{v.name}' has no value in scope")
            state[v.name] = jnp.asarray(val)

    fn = _infer_fn(program, feed_names, fetch_names, state)
    specs_by_batch = {int(b): _feed_specs(program, feed_names, b)
                      for b in example_batches}
    return _write_artifacts(dirname, fn, specs_by_batch, feed_names,
                            fetch_names)


def _write_artifacts(dirname, fn, specs_by_batch, feed_names, fetch_names):
    """Serialize fn once per signature + the shared aot_meta.json (the ONE
    place that knows the artifact layout load_aot_model reads)."""
    os.makedirs(dirname, exist_ok=True)
    sigs = []
    for batch, specs in specs_by_batch.items():
        exp = jax.export.export(jax.jit(fn))(specs)
        fname = f"sig_b{batch}.jaxexp"
        with open(os.path.join(dirname, fname), "wb") as f:
            f.write(exp.serialize())
        sigs.append({"batch": batch, "file": fname,
                     "shapes": {k: list(s.shape) for k, s in specs.items()},
                     "dtypes": {k: str(np.dtype(s.dtype))
                                for k, s in specs.items()}})
    meta = {"feed_names": list(feed_names),
            "fetch_names": list(fetch_names), "signatures": sigs}
    with open(os.path.join(dirname, "aot_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return [s["file"] for s in sigs]


def save_aot_callable(dirname, fn, example_feeds, fetch_names=("out",)):
    """Export an arbitrary jittable fn(feeds dict) -> [outputs] as an AOT
    artifact (one signature: the example shapes). Backs dygraph
    TracedLayer.save_inference_model."""
    specs = {}
    for k, v in example_feeds.items():
        arr = jnp.asarray(v)
        specs[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
    first = next(iter(specs.values()))
    batch = int(first.shape[0]) if first.shape else 1
    return _write_artifacts(dirname, fn, {batch: specs}, list(specs),
                            fetch_names)


class AotModel:
    """Serving handle over deserialized signatures; run() dispatches on
    the feed batch size (exact match required — the artifact is
    shape-specialized by design)."""

    def __init__(self, meta, exported):
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]
        self._by_batch = exported          # batch -> jax.export.Exported

    def batch_sizes(self):
        return sorted(self._by_batch)

    def run(self, feeds):
        first = feeds[self.feed_names[0]]
        batch = int(np.asarray(first).shape[0])
        exp = self._by_batch.get(batch)
        if exp is None:
            raise ValueError(
                f"no compiled signature for batch {batch}; available: "
                f"{self.batch_sizes()} (export more via example_batches)")
        args = {k: jnp.asarray(feeds[k]) for k in self.feed_names}
        return [np.asarray(o) for o in exp.call(args)]

    __call__ = run


def load_aot_model(dirname):
    with open(os.path.join(dirname, "aot_meta.json")) as f:
        meta = json.load(f)
    exported = {}
    for sig in meta["signatures"]:
        with open(os.path.join(dirname, sig["file"]), "rb") as f:
            exported[sig["batch"]] = jax.export.deserialize(f.read())
    return AotModel(meta, exported)
