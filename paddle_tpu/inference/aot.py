"""AOT serving artifacts: compile a Program once, serialize, serve anywhere.

Parity: the reference's inference engine ahead-of-time story
(paddle/fluid/inference — an optimized, self-contained artifact the
serving fleet loads without the training stack). TPU-native mapping:
`jax.export` serializes the traced+lowered StableHLO (params baked in as
constants) per feed-shape signature; `load_aot_model` deserializes and
calls it — no Program rebuild, no op registry, no scope at serve time.

    save_aot_model(path, program, feed_names, fetch_names,
                   example_batches=[1, 8], scope=scope)
    model = load_aot_model(path)
    out = model.run({"x": x})          # picks the matching signature
"""

import json
import os

import numpy as np

import jax
import jax.numpy as jnp


def _infer_fn(program, feed_names, fetch_names, state):
    """Pure fn(feeds dict) -> [fetches], closing over the param values
    (they become constants in the exported artifact)."""
    from ..core.executor import _lower_block

    gb = program.global_block()

    def fn(feeds):
        env = dict(state)
        env.update(feeds)
        env["@RNG@"] = jax.random.PRNGKey(0)
        _lower_block(gb, env, program, is_test=True)
        return [env[n] for n in fetch_names]

    return fn


def _feed_specs(program, feed_names, batch):
    specs = {}
    gb = program.global_block()
    for name in feed_names:
        v = gb.var(name)
        dims = [int(d) for d in v.shape]
        if not dims:
            raise ValueError(f"feed '{name}' has no declared shape")
        if dims[0] == -1:
            dims[0] = batch
        elif dims[0] != batch:
            # fluid.data with a STATIC leading batch: the var shape already
            # includes it; a different requested bucket can't exist
            raise ValueError(
                f"feed '{name}' declares a static batch {dims[0]}; "
                f"example_batches must be ({dims[0]},), got {batch}")
        specs[name] = jax.ShapeDtypeStruct(tuple(dims),
                                           jnp.dtype(v.dtype))
    return specs


def save_aot_model(dirname, program, feed_names, fetch_names,
                   example_batches=(1,), scope=None):
    """Export one serialized artifact per batch size. The program should
    be an inference graph (clone(for_test=True) / load_inference_model
    output); params come from `scope` (default global)."""
    from ..core.executor import global_scope

    scope = scope or global_scope()
    fetch_names = [v.name if hasattr(v, "name") else v for v in fetch_names]
    state = {}
    gb = program.global_block()
    for v in gb.vars.values():
        if v.persistable and v.name not in ("feed", "fetch"):
            val = scope.get(v.name)
            if val is None:
                raise ValueError(f"param '{v.name}' has no value in scope")
            state[v.name] = jnp.asarray(val)

    fn = _infer_fn(program, feed_names, fetch_names, state)
    os.makedirs(dirname, exist_ok=True)
    sigs = []
    for batch in example_batches:
        specs = _feed_specs(program, feed_names, batch)
        exp = jax.export.export(jax.jit(fn))(specs)
        fname = f"sig_b{batch}.jaxexp"
        with open(os.path.join(dirname, fname), "wb") as f:
            f.write(exp.serialize())
        sigs.append({"batch": int(batch), "file": fname,
                     "shapes": {k: list(s.shape) for k, s in specs.items()},
                     "dtypes": {k: str(np.dtype(s.dtype))
                                for k, s in specs.items()}})
    meta = {"feed_names": list(feed_names), "fetch_names": fetch_names,
            "signatures": sigs}
    with open(os.path.join(dirname, "aot_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return [s["file"] for s in sigs]


class AotModel:
    """Serving handle over deserialized signatures; run() dispatches on
    the feed batch size (exact match required — the artifact is
    shape-specialized by design)."""

    def __init__(self, meta, exported):
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]
        self._by_batch = exported          # batch -> jax.export.Exported

    def batch_sizes(self):
        return sorted(self._by_batch)

    def run(self, feeds):
        first = feeds[self.feed_names[0]]
        batch = int(np.asarray(first).shape[0])
        exp = self._by_batch.get(batch)
        if exp is None:
            raise ValueError(
                f"no compiled signature for batch {batch}; available: "
                f"{self.batch_sizes()} (export more via example_batches)")
        args = {k: jnp.asarray(feeds[k]) for k in self.feed_names}
        return [np.asarray(o) for o in exp.call(args)]

    __call__ = run


def load_aot_model(dirname):
    with open(os.path.join(dirname, "aot_meta.json")) as f:
        meta = json.load(f)
    exported = {}
    for sig in meta["signatures"]:
        with open(os.path.join(dirname, sig["file"]), "rb") as f:
            exported[sig["batch"]] = jax.export.deserialize(f.read())
    return AotModel(meta, exported)
