"""Micro-batching serving loop over the native request queue
(csrc/serve_queue.cc).

Parity target: the reference's C++ inference server (paddle/fluid/
inference/api — AnalysisPredictor behind a request-grouping service
loop). TPU-native twist: the engine is one cached XLA executable per
batch bucket (Predictor.predict_batch), so grouping concurrent
requests into buckets is what keeps the MXU fed; singles would leave
it >90% idle.

The latency/throughput contract is the standard pair: a batch launches
when `max_batch` requests are queued OR the oldest has waited
`max_delay_ms`. All waiting happens in C++ off the GIL; request
payloads stay in Python (the queue moves int64 ticket ids only).

    server = BatchingServer(predictor, max_batch=8, max_delay_ms=2.0)
    fut = server.submit({"x": np.array([[...]])})   # any thread
    out = fut.result()                              # this request's rows
    server.close()
"""

import ctypes
import threading
from concurrent.futures import Future

import numpy as np

from ..utils.native import build_and_load

_lib = None
_lib_lock = threading.Lock()


def load_library():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = build_and_load("serve_queue.cc", "libservequeue.so")
        lib.sq_create.restype = ctypes.c_void_p
        lib.sq_create.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.sq_submit.restype = ctypes.c_int
        lib.sq_submit.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sq_next_batch.restype = ctypes.c_int64
        lib.sq_next_batch.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_int64, ctypes.c_int64]
        lib.sq_pending.restype = ctypes.c_int64
        lib.sq_pending.argtypes = [ctypes.c_void_p]
        lib.sq_close.argtypes = [ctypes.c_void_p]
        lib.sq_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    try:
        load_library()
        return True
    except Exception:
        return False


class BatchingServer:
    """Group concurrent single-request predicts into bucket-sized
    batches. One worker thread owns the predictor (XLA dispatch is not
    re-entrant-friendly anyway); any number of client threads submit."""

    def __init__(self, predictor, max_batch=8, max_delay_ms=2.0):
        self._lib = load_library()
        self._pred = predictor
        self._q = self._lib.sq_create(int(max_batch),
                                      int(max_delay_ms * 1000))
        self._reqs = {}
        self._reqs_lock = threading.Lock()
        self._next_id = 0
        self._max_batch = int(max_batch)
        self._closed = False
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def submit(self, feeds):
        """feeds: dict name -> (1, ...) or (k, ...) array. Returns a
        Future resolving to this request's output rows (list, one array
        per model output)."""
        feeds = {k: np.asarray(v) for k, v in feeds.items()}
        fut = Future()
        # sq_submit runs INSIDE the lock (it never blocks) so close()
        # cannot destroy the native handle between our closed-check and
        # the call
        with self._reqs_lock:
            if self._closed or self._q is None:
                raise RuntimeError("BatchingServer is closed")
            rid = self._next_id
            self._next_id += 1
            self._reqs[rid] = (feeds, fut)
            if self._lib.sq_submit(self._q, rid) != 0:
                self._reqs.pop(rid, None)
                raise RuntimeError("BatchingServer is closed")
        return fut

    def _serve(self):
        ids = (ctypes.c_int64 * self._max_batch)()
        while True:
            n = self._lib.sq_next_batch(self._q, ids, self._max_batch,
                                        200_000)
            if n < 0:
                return                      # closed and drained
            if n == 0:
                continue                    # poll timeout — loop
            batch = []
            with self._reqs_lock:
                for i in range(n):
                    rid = ids[i]
                    feeds_i, fut = self._reqs.pop(rid)
                    # a client may have cancelled while queued; claiming
                    # the future here also makes a later set_result safe
                    if fut.set_running_or_notify_cancel():
                        batch.append((rid, feeds_i, fut))
            if not batch:
                continue
            try:
                feeds = {
                    k: np.concatenate([f[k] for _, f, _ in batch])
                    for k in batch[0][1]
                }
                outs = self._pred.predict_batch(feeds)
                row = 0
                for _, f, fut in batch:
                    k = next(iter(f))
                    rows = f[k].shape[0]
                    fut.set_result([o[row:row + rows] for o in outs])
                    row += rows
            except Exception as e:          # noqa: BLE001 — fan the error out
                for _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    def pending(self):
        with self._reqs_lock:
            if self._q is None:
                return 0
            return int(self._lib.sq_pending(self._q))

    def close(self, join_timeout=30):
        """Drain and stop. The native queue is freed ONLY once the
        worker thread has exited — if the worker is stuck inside a long
        engine call (an XLA bucket compile can take minutes) the handle
        is deliberately leaked instead of freed under its feet."""
        with self._reqs_lock:
            if self._closed:
                return
            self._closed = True
        self._lib.sq_close(self._q)
        self._worker.join(timeout=join_timeout)
        if self._worker.is_alive():
            import warnings
            warnings.warn("BatchingServer worker still busy after "
                          f"{join_timeout}s — leaking the native queue "
                          "handle rather than freeing it mid-use")
            return
        with self._reqs_lock:
            self._lib.sq_destroy(self._q)
            self._q = None
