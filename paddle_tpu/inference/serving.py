"""Micro-batching serving loop over the native request queue
(csrc/serve_queue.cc).

Parity target: the reference's C++ inference server (paddle/fluid/
inference/api — AnalysisPredictor behind a request-grouping service
loop). TPU-native twist: the engine is one cached XLA executable per
batch bucket (Predictor.predict_batch), so grouping concurrent
requests into buckets is what keeps the MXU fed; singles would leave
it >90% idle.

The latency/throughput contract is the standard pair: a batch launches
when `max_batch` requests are queued OR the oldest has waited
`max_delay_ms`. All waiting happens in C++ off the GIL; request
payloads stay in Python (the queue moves int64 ticket ids only).

    server = BatchingServer(predictor, max_batch=8, max_delay_ms=2.0)
    fut = server.submit({"x": np.array([[...]])})   # any thread
    out = fut.result()                              # this request's rows
    server.close()

Containers without a C++ toolchain (`available() == False`) fall back
to a pure-Python queue with the same batch/deadline contract, so the
serving stack runs (and its tests run) everywhere. Generation
workloads with ragged lengths belong to `paddle_tpu.serving`'s
continuous-batching GenerationServer instead; this loop batches
fixed-shape one-shot predicts.
"""

import ctypes
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..utils.native import build_and_load

_lib = None
_lib_lock = threading.Lock()


def load_library():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = build_and_load("serve_queue.cc", "libservequeue.so")
        lib.sq_create.restype = ctypes.c_void_p
        lib.sq_create.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.sq_submit.restype = ctypes.c_int
        lib.sq_submit.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sq_next_batch.restype = ctypes.c_int64
        lib.sq_next_batch.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_int64, ctypes.c_int64]
        lib.sq_pending.restype = ctypes.c_int64
        lib.sq_pending.argtypes = [ctypes.c_void_p]
        lib.sq_close.argtypes = [ctypes.c_void_p]
        lib.sq_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    try:
        load_library()
        return True
    except Exception:
        return False


class _PyQueue:
    """Pure-Python fallback for csrc/serve_queue.cc — same contract
    (batch launches at max_batch OR when the oldest request has waited
    max_delay), built on a Condition instead of the off-GIL C++ wait.
    Used when the container has no compiler (`available() == False`),
    so the serving stack and its tests run everywhere; the native queue
    stays the default where it builds."""

    def __init__(self, max_batch, max_delay_us):
        self._max_batch = int(max_batch)
        self._max_delay_s = max_delay_us / 1e6
        self._cv = threading.Condition()
        self._items = []                    # (rid, enqueue_time)
        self._closed = False

    def submit(self, rid):
        with self._cv:
            if self._closed:
                return -1
            self._items.append((rid, time.monotonic()))
            self._cv.notify_all()
            return 0

    def next_batch(self, max_n, poll_timeout_us):
        """-> list of rids ([] on poll timeout), or None once closed
        AND drained — mirrors sq_next_batch's n / 0 / -1."""
        poll_deadline = time.monotonic() + poll_timeout_us / 1e6
        with self._cv:
            while True:
                now = time.monotonic()
                if self._items:
                    oldest = self._items[0][1]
                    if (len(self._items) >= self._max_batch
                            or now - oldest >= self._max_delay_s
                            or self._closed):
                        n = min(len(self._items), max_n,
                                self._max_batch)
                        out = [rid for rid, _ in self._items[:n]]
                        del self._items[:n]
                        return out
                    wait = min(oldest + self._max_delay_s,
                               poll_deadline) - now
                elif self._closed:
                    return None             # closed and drained
                else:
                    wait = poll_deadline - now
                if now >= poll_deadline:
                    return []               # poll timeout — caller loops
                self._cv.wait(timeout=max(wait, 0.0))

    def pending(self):
        with self._cv:
            return len(self._items)

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def destroy(self):
        pass


class _NativeQueue:
    """ctypes adapter giving csrc/serve_queue.cc the same Python-level
    interface as _PyQueue (all waiting stays in C++ off the GIL)."""

    def __init__(self, max_batch, max_delay_us):
        self._lib = load_library()
        self._q = self._lib.sq_create(int(max_batch), int(max_delay_us))
        self._max_batch = int(max_batch)
        self._ids = (ctypes.c_int64 * self._max_batch)()

    def submit(self, rid):
        return self._lib.sq_submit(self._q, rid)

    def next_batch(self, max_n, poll_timeout_us):
        n = self._lib.sq_next_batch(self._q, self._ids,
                                    min(max_n, self._max_batch),
                                    poll_timeout_us)
        if n < 0:
            return None
        return [self._ids[i] for i in range(n)]

    def pending(self):
        return int(self._lib.sq_pending(self._q))

    def close(self):
        self._lib.sq_close(self._q)

    def destroy(self):
        self._lib.sq_destroy(self._q)


def _feed_sig(feeds):
    """(keys, per-key trailing dims + dtype) — everything a batch
    np.concatenate over axis 0 requires to agree across requests."""
    return tuple(sorted(
        (k, tuple(v.shape[1:]), v.dtype.str) for k, v in feeds.items()))


class BatchingServer:
    """Group concurrent single-request predicts into bucket-sized
    batches. One worker thread owns the predictor (XLA dispatch is not
    re-entrant-friendly anyway); any number of client threads submit.

    backend: "native" (csrc serve_queue), "python" (fallback), or
    "auto" — native when the toolchain can build it, python otherwise."""

    def __init__(self, predictor, max_batch=8, max_delay_ms=2.0,
                 backend="auto"):
        if backend == "auto":
            backend = "native" if available() else "python"
        if backend == "native":
            self._q = _NativeQueue(max_batch, int(max_delay_ms * 1000))
        elif backend == "python":
            self._q = _PyQueue(max_batch, int(max_delay_ms * 1000))
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self._pred = predictor
        self._reqs = {}
        self._reqs_lock = threading.Lock()
        self._next_id = 0
        self._max_batch = int(max_batch)
        self._closed = False
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def submit(self, feeds):
        """feeds: dict name -> (1, ...) or (k, ...) array. Returns a
        Future resolving to this request's output rows (list, one array
        per model output).

        The feed signature (key set + trailing dims + dtype) must match
        every request currently queued: co-batched feeds concatenate on
        axis 0, so a mismatch is THIS caller's error and raises here,
        instead of poisoning the whole batch and fanning one confusing
        concatenate exception to every co-batched future."""
        feeds = {k: np.asarray(v) for k, v in feeds.items()}
        sig = _feed_sig(feeds)
        fut = Future()
        # queue submit runs INSIDE the lock (it never blocks) so close()
        # cannot destroy the native handle between our closed-check and
        # the call
        with self._reqs_lock:
            if self._closed or self._q is None:
                raise RuntimeError("BatchingServer is closed")
            if self._reqs:
                first_sig = next(iter(self._reqs.values()))[2]
                if sig != first_sig:
                    raise ValueError(
                        "feed signature mismatch with the queued batch: "
                        f"queued {first_sig} vs submitted {sig} — keys, "
                        "trailing dims and dtypes must agree for "
                        "requests to co-batch")
            rid = self._next_id
            self._next_id += 1
            self._reqs[rid] = (feeds, fut, sig)
            if self._q.submit(rid) != 0:
                self._reqs.pop(rid, None)
                raise RuntimeError("BatchingServer is closed")
        return fut

    def _serve(self):
        while True:
            rids = self._q.next_batch(self._max_batch, 200_000)
            if rids is None:
                return                      # closed and drained
            if not rids:
                continue                    # poll timeout — loop
            batch = []
            with self._reqs_lock:
                for rid in rids:
                    feeds_i, fut, _sig = self._reqs.pop(rid)
                    # a client may have cancelled while queued; claiming
                    # the future here also makes a later set_result safe
                    if fut.set_running_or_notify_cancel():
                        batch.append((rid, feeds_i, fut))
            if not batch:
                continue
            try:
                feeds = {
                    k: np.concatenate([f[k] for _, f, _ in batch])
                    for k in batch[0][1]
                }
                outs = self._pred.predict_batch(feeds)
                row = 0
                for _, f, fut in batch:
                    k = next(iter(f))
                    rows = f[k].shape[0]
                    fut.set_result([o[row:row + rows] for o in outs])
                    row += rows
            except Exception as e:          # noqa: BLE001 — fan the error out
                for _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    def pending(self):
        with self._reqs_lock:
            if self._q is None:
                return 0
            return self._q.pending()

    def close(self, join_timeout=30):
        """Drain and stop. The native queue is freed ONLY once the
        worker thread has exited — if the worker is stuck inside a long
        engine call (an XLA bucket compile can take minutes) the handle
        is deliberately leaked instead of freed under its feet."""
        with self._reqs_lock:
            if self._closed:
                return
            self._closed = True
        self._q.close()
        self._worker.join(timeout=join_timeout)
        if self._worker.is_alive():
            import warnings
            warnings.warn("BatchingServer worker still busy after "
                          f"{join_timeout}s — leaking the native queue "
                          "handle rather than freeing it mid-use")
            return
        with self._reqs_lock:
            self._q.destroy()
            self._q = None
