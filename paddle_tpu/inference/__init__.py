"""Inference engine (SURVEY.md §2.10).

Parity: paddle/fluid/inference (AnalysisPredictor / NativePaddlePredictor).
The reference's engine loads a ProgramDesc, runs IR passes, and executes
op-by-op on a stream; TPU-native the 'engine' is: load program+params ->
trace once -> one AOT-compiled XLA executable per input signature, with
donated buffers and optional bf16. KV-cache autoregressive decoding lives in
decoding.py; ragged generation traffic (continuous batching over a paged
KV cache) lives in paddle_tpu.serving, reachable from here via
AnalysisConfig.enable_generation + Predictor.generation_server.
"""

from .predictor import Predictor, create_predictor, AnalysisConfig
from .aot import save_aot_model, load_aot_model, AotModel
from .decoding import greedy_decode, beam_decode
from .postprocess import multiclass_nms_host

__all__ = ["Predictor", "create_predictor", "AnalysisConfig",
           "greedy_decode", "beam_decode", "multiclass_nms_host"]
