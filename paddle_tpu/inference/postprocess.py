"""Host-side detection postprocess: variable-length multiclass NMS.

Parity: paddle/fluid/operators/detection/multiclass_nms_op.cc and the
inference engine's CPU postprocess. The in-graph `multiclass_nms` op is
the static-shape padded variant (XLA-legal, SURVEY.md design decision 4);
this module is the predictor-side truth: dense (boxes, scores) leave the
chip, and a native C++ kernel (csrc/nms.cc, built on first use like the
prefetch ring) prunes them into per-image variable-length results — the
LoD-shaped output the reference returns. Falls back to a numpy
implementation when no compiler is available.
"""

import ctypes
import threading

import numpy as np

from ..utils.native import build_and_load

_lib = None
_lib_failed = False
_lib_lock = threading.Lock()


def _load_library():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            lib = build_and_load("nms.cc", "libnms.so")
            f32p = ctypes.POINTER(ctypes.c_float)
            i32p = ctypes.POINTER(ctypes.c_int)
            lib.pt_multiclass_nms_batch.restype = ctypes.c_int
            lib.pt_multiclass_nms_batch.argtypes = [
                f32p, f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                f32p, ctypes.c_int, i32p]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def _iou(a, b, normalized):
    off = 0.0 if normalized else 1.0
    ix1 = np.maximum(a[0], b[:, 0])
    iy1 = np.maximum(a[1], b[:, 1])
    ix2 = np.minimum(a[2], b[:, 2])
    iy2 = np.minimum(a[3], b[:, 3])
    inter = np.maximum(ix2 - ix1 + off, 0) * np.maximum(iy2 - iy1 + off, 0)
    aa = (a[2] - a[0] + off) * (a[3] - a[1] + off)
    ab = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    union = aa + ab - inter
    return np.where(union <= 0, 0.0, inter / np.maximum(union, 1e-30))


def _nms_numpy_image(boxes, scores, score_thresh, nms_thresh, eta,
                     nms_top_k, keep_top_k, background, normalized):
    dets = []
    c, m = scores.shape
    for cls in range(c):
        if cls == background:
            continue
        idx = np.nonzero(scores[cls] > score_thresh)[0]
        idx = idx[np.argsort(-scores[cls][idx], kind="stable")]
        if nms_top_k > -1:
            idx = idx[:nms_top_k]
        kept = []
        adaptive = nms_thresh
        for i in idx:
            if kept and _iou(boxes[i], boxes[np.array(kept)],
                             normalized).max() > adaptive:
                continue
            kept.append(i)
            if eta < 1.0 and adaptive > 0.5:
                adaptive *= eta
        for i in kept:
            dets.append((float(scores[cls][i]), cls, i))
    dets.sort(key=lambda d: -d[0])
    if keep_top_k > -1:
        dets = dets[:keep_top_k]
    out = np.array([[cls, sc, *boxes[i]] for sc, cls, i in dets],
                   np.float32).reshape(-1, 6)
    return out


def multiclass_nms_host(bboxes, scores, score_threshold=0.01,
                        nms_threshold=0.3, nms_eta=1.0, nms_top_k=-1,
                        keep_top_k=-1, background_label=0, normalized=True):
    """bboxes (N, M, 4), scores (N, C, M) -> (detections (total, 6),
    lod offsets (N+1,)). Rows are [class, score, x1, y1, x2, y2], sorted
    best-first per image — the reference's LoD output contract."""
    bboxes = np.ascontiguousarray(bboxes, np.float32)
    scores = np.ascontiguousarray(scores, np.float32)
    n, m, _ = bboxes.shape
    _, c, m2 = scores.shape
    if m != m2:
        raise ValueError(f"boxes M={m} vs scores M={m2}")
    lib = _load_library()
    if lib is not None:
        # worst-case rows/image without holding m*(c-1) rows for
        # detector-scale inputs; overflow falls through to numpy
        cap_per = keep_top_k if keep_top_k > -1 else (
            nms_top_k * max(c - 1, 1) if nms_top_k > -1
            else min(m * max(c - 1, 1), 4096))
        cap = max(n * max(cap_per, 1), 1)
        out = np.empty((cap, 6), np.float32)
        counts = np.empty(n, np.int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int)
        total = lib.pt_multiclass_nms_batch(
            bboxes.ctypes.data_as(f32p), scores.ctypes.data_as(f32p),
            n, m, c, score_threshold, nms_threshold, nms_eta, nms_top_k,
            keep_top_k, background_label, int(normalized),
            out.ctypes.data_as(f32p), cap, counts.ctypes.data_as(i32p))
        if total >= 0:
            lod = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
            return out[:total].copy(), lod
    # numpy fallback (no g++, or capacity overflow)
    pieces = [_nms_numpy_image(bboxes[i], scores[i], score_threshold,
                               nms_threshold, nms_eta, nms_top_k,
                               keep_top_k, background_label, normalized)
              for i in range(n)]
    lod = np.concatenate([[0], np.cumsum([len(p) for p in pieces])]
                         ).astype(np.int64)
    dets = (np.concatenate(pieces, axis=0) if pieces
            else np.zeros((0, 6), np.float32))
    return dets, lod
