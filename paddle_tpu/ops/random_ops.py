"""Random ops.

Parity: paddle/fluid/operators/{gaussian_random,uniform_random,truncated_
gaussian_random,random_crop,sampling_id}_op.* — re-keyed onto JAX's counter
based PRNG: each op gets a deterministic fold_in of the step key, so runs are
reproducible per (seed, step, op) — the TPU answer to cuRAND states.
"""

import jax
import jax.numpy as jnp

from . import register, DEVICE_INT
from .tensor_ops import _np_dtype


@register("gaussian_random")
def gaussian_random(ctx):
    shape = tuple(ctx.attr("shape"))
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": mean + std * jax.random.normal(ctx.rng(), shape, dtype=dtype)}


@register("uniform_random", "uniform_random_batch_size_like")
def uniform_random(ctx):
    if ctx.has_in("Input"):
        ref = ctx.in_("Input")
        shape = list(ctx.attr("shape"))
        shape[ctx.attr("output_dim_idx", 0)] = ref.shape[ctx.attr("input_dim_idx", 0)]
        shape = tuple(shape)
    else:
        shape = tuple(ctx.attr("shape"))
    lo = ctx.attr("min", -1.0)
    hi = ctx.attr("max", 1.0)
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jax.random.uniform(ctx.rng(), shape, dtype=dtype,
                                      minval=lo, maxval=hi)}


@register("gaussian_random_batch_size_like")
def gaussian_random_batch_size_like(ctx):
    ref = ctx.in_("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = ref.shape[ctx.attr("input_dim_idx", 0)]
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    return {"Out": mean + std * jax.random.normal(ctx.rng(), tuple(shape))}


@register("truncated_gaussian_random")
def truncated_gaussian_random(ctx):
    shape = tuple(ctx.attr("shape"))
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    out = jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, dtype=dtype)
    return {"Out": mean + std * out}


@register("randint")
def randint(ctx):
    return {"Out": jax.random.randint(ctx.rng(), tuple(ctx.attr("shape")),
                                      ctx.attr("low", 0), ctx.attr("high"))}


@register("sampling_id")
def sampling_id(ctx):
    x = ctx.in_("X")  # (N, C) probabilities
    idx = jax.random.categorical(ctx.rng(), jnp.log(jnp.clip(x, 1e-20, None)), axis=-1)
    return {"Out": idx.astype(DEVICE_INT)}


@register("random_crop")
def random_crop(ctx):
    x = ctx.in_("X")
    shape = ctx.attr("shape")  # crop shape for trailing dims
    ndim_crop = len(shape)
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        dim = x.shape[x.ndim - ndim_crop + i]
        k = jax.random.fold_in(key, i)
        starts.append(jax.random.randint(k, (), 0, dim - s + 1))
    start_idx = [0] * (x.ndim - ndim_crop) + [int(0)] * ndim_crop
    full = list(x.shape[:x.ndim - ndim_crop]) + list(shape)
    dyn_start = [jnp.asarray(0)] * (x.ndim - ndim_crop) + starts
    out = jax.lax.dynamic_slice(x, dyn_start, full)
    return {"Out": out}


@register("multinomial")
def multinomial(ctx):
    x = ctx.in_("X")
    n = ctx.attr("num_samples", 1)
    keys = jax.random.split(ctx.rng(), n)
    logits = jnp.log(jnp.clip(x, 1e-20, None))
    samples = jnp.stack([jax.random.categorical(k, logits, axis=-1) for k in keys], -1)
    return {"Out": samples.astype(DEVICE_INT)}


@register("bernoulli")
def bernoulli(ctx):
    x = ctx.in_("X")
    return {"Out": jax.random.bernoulli(ctx.rng(), x).astype(x.dtype)}
