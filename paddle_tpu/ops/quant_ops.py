"""Fake-quantization ops (QAT/PTQ).

Parity: paddle/fluid/operators/fake_quantize_op.* —
fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
fake_quantize_moving_average_abs_max, fake_quantize_dequantize variants.

TPU-native: quantize-dequantize is a pure function with a straight-through
estimator (custom_vjp identity) so jax.grad flows through the rounding; the
moving-average scale is ordinary state threaded through the env like
batch-norm stats (no mutable buffers inside jit).
"""

import jax
import jax.numpy as jnp

from . import register


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_round_fwd(x):
    return jnp.round(x), None


def _ste_round_bwd(_, g):
    return (g,)


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def quant_dequant(x, scale, bits=8):
    """Symmetric fake quant: round(clip(x)/scale * qmax) * scale / qmax,
    gradient = identity inside the clip range (STE)."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(scale, 1e-8)
    xc = jnp.clip(x, -scale, scale)
    return _ste_round(xc / scale * qmax) * scale / qmax


def abs_max(x):
    return jnp.max(jnp.abs(x))


def channel_abs_max(x, channel_axis=0):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    return jnp.max(jnp.abs(x), axis=axes)


@register("fake_quantize_dequantize_abs_max", "fake_quantize_abs_max")
def fake_quantize_abs_max(ctx):
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    scale = abs_max(x)
    out = quant_dequant(x, scale, bits)
    return {"Out": out, "OutScale": scale}


@register("fake_channel_wise_quantize_dequantize_abs_max",
          "fake_channel_wise_quantize_abs_max")
def fake_channel_wise_quantize_abs_max(ctx):
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    axis = ctx.attr("quant_axis", 0)
    scale = channel_abs_max(x, axis)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = quant_dequant(x, scale.reshape(shape), bits)
    return {"Out": out, "OutScale": scale}


@register("quantize_dequantize_static_scale")
def quantize_dequantize_static_scale(ctx):
    """PTQ path: scale calibrated offline, carried as an attr."""
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    scale = ctx.attr("scale", 1.0)
    return {"Out": quant_dequant(x, jnp.float32(scale), bits)}


@register("fake_quantize_dequantize_moving_average_abs_max",
          "fake_quantize_moving_average_abs_max")
def fake_quantize_moving_average_abs_max(ctx):
    """Activation fake-quant: scale is an EMA of batch abs-max. State vars
    (InScale/OutScale) thread through the env; in test mode the stored
    scale is used without updating."""
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    rate = ctx.attr("moving_rate", 0.9)
    in_scale = ctx.in_("InScale")
    if ctx.is_test:
        scale = in_scale
    else:
        cur = abs_max(x)
        scale = rate * in_scale + (1.0 - rate) * cur
    out = quant_dequant(x, jnp.reshape(scale, ()), bits)
    return {"Out": out, "OutScale": scale}
