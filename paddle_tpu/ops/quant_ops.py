"""Fake-quantization ops (QAT/PTQ).

Parity: paddle/fluid/operators/fake_quantize_op.* —
fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
fake_quantize_moving_average_abs_max, fake_quantize_dequantize variants.

TPU-native: quantize-dequantize is a pure function with a straight-through
estimator (custom_vjp identity) so jax.grad flows through the rounding; the
moving-average scale is ordinary state threaded through the env like
batch-norm stats (no mutable buffers inside jit).
"""

import jax
import jax.numpy as jnp

from . import register


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_round_fwd(x):
    return jnp.round(x), None


def _ste_round_bwd(_, g):
    return (g,)


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def quant_dequant(x, scale, bits=8):
    """Symmetric fake quant: round(clip(x)/scale * qmax) * scale / qmax,
    gradient = identity inside the clip range (STE)."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(scale, 1e-8)
    xc = jnp.clip(x, -scale, scale)
    return _ste_round(xc / scale * qmax) * scale / qmax


def abs_max(x):
    return jnp.max(jnp.abs(x))


def channel_abs_max(x, channel_axis=0):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    return jnp.max(jnp.abs(x), axis=axes)


@register("fake_quantize_dequantize_abs_max", "fake_quantize_abs_max")
def fake_quantize_abs_max(ctx):
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    scale = abs_max(x)
    out = quant_dequant(x, scale, bits)
    return {"Out": out, "OutScale": scale}


@register("fake_channel_wise_quantize_dequantize_abs_max",
          "fake_channel_wise_quantize_abs_max")
def fake_channel_wise_quantize_abs_max(ctx):
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    axis = ctx.attr("quant_axis", 0)
    scale = channel_abs_max(x, axis)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = quant_dequant(x, scale.reshape(shape), bits)
    return {"Out": out, "OutScale": scale}


@register("quantize_dequantize_static_scale")
def quantize_dequantize_static_scale(ctx):
    """PTQ path: scale calibrated offline, carried as an attr."""
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    scale = ctx.attr("scale", 1.0)
    return {"Out": quant_dequant(x, jnp.float32(scale), bits)}


@register("fake_quantize_dequantize_moving_average_abs_max",
          "fake_quantize_moving_average_abs_max")
def fake_quantize_moving_average_abs_max(ctx):
    """Activation fake-quant: scale is an EMA of batch abs-max. State vars
    (InScale/OutScale) thread through the env; in test mode the stored
    scale is used without updating."""
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    rate = ctx.attr("moving_rate", 0.9)
    in_scale = ctx.in_("InScale")
    if ctx.is_test:
        scale = in_scale
    else:
        cur = abs_max(x)
        scale = rate * in_scale + (1.0 - rate) * cur
    out = quant_dequant(x, jnp.reshape(scale, ()), bits)
    return {"Out": out, "OutScale": scale}


@register("fake_dequantize_max_abs")
def fake_dequantize_max_abs(ctx):
    """Parity: fake_dequantize_op: Out = X * Scale / max_range (int8
    tensor back to float with a recorded abs-max scale)."""
    x = ctx.in_("X").astype(jnp.float32)
    scale = ctx.in_("Scale").reshape(())
    max_range = float(ctx.attr("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register("fake_channel_wise_dequantize_max_abs")
def fake_channel_wise_dequantize_max_abs(ctx):
    """Parity: channel-wise variant — Scales[0] is per-channel; the
    optional second scale (activation) divides out like the reference's
    two-level max_range."""
    x = ctx.in_("X").astype(jnp.float32)
    scales = ctx.in_("Scales")
    if isinstance(scales, (list, tuple)):
        ch, rest = scales[0], scales[1:]
    else:
        ch, rest = scales, ()
    bits = ctx.attr("quant_bits", [8])
    axis = ctx.attr("quant_axis", 0)
    qmax = float(2 ** (int(bits[0]) - 1) - 1)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = x * ch.reshape(shape) / qmax
    for i, s in enumerate(rest):
        b = int(bits[i + 1]) if i + 1 < len(bits) else 8
        out = out * s.reshape(()) / float(2 ** (b - 1) - 1)
    return {"Out": out}


@register("fake_quantize_range_abs_max")
def fake_quantize_range_abs_max(ctx):
    """Parity: range_abs_max QAT activation quant — the reference keeps
    a window_size ring of batch abs-maxes and uses its max; that state
    is design-reduced to an EMA (same role: a smoothed activation scale
    that FORGETS old outliers, unlike a monotone running max), matching
    fake_quantize_moving_average_abs_max above."""
    x = ctx.in_("X")
    bits = ctx.attr("bit_length", 8)
    rate = ctx.attr("moving_rate", 0.9)
    in_scale = ctx.in_("InScale").reshape(())
    if ctx.is_test:
        scale = in_scale
    else:
        cur = abs_max(x)
        # first step (scale state still 0) adopts the batch max outright
        scale = jnp.where(in_scale > 0,
                          rate * in_scale + (1.0 - rate) * cur, cur)
    return {"Out": quant_dequant(x, scale, bits), "OutScale": scale}


@register("moving_average_abs_max_scale")
def moving_average_abs_max_scale(ctx):
    """Parity: scale observer — passthrough output, EMA abs-max scale
    state (used by PTQ calibration passes)."""
    x = ctx.in_("X")
    rate = ctx.attr("moving_rate", 0.9)
    in_scale = ctx.in_("InScale").reshape(()) if ctx.has_in("InScale") \
        else jnp.float32(0.0)
    scale = in_scale if ctx.is_test else \
        rate * in_scale + (1.0 - rate) * abs_max(x)
    return {"Out": x, "OutScale": scale}
