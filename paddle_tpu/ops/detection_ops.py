"""Detection ops (subset).

Parity: paddle/fluid/operators/detection/{roi_pool,roi_align,prior_box,
iou_similarity,box_coder,yolo_box}_op.* — enough for the detection demo
models; NMS runs as a host-side utility (paddle_tpu.layers.detection).
"""

import jax
import jax.numpy as jnp

from . import register


@register("iou_similarity")
def iou_similarity(ctx):
    x = ctx.in_("X")  # (N, 4) xmin,ymin,xmax,ymax
    y = ctx.in_("Y")  # (M, 4)
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / jnp.maximum(area_x[:, None] + area_y[None, :] - inter, 1e-10)}


def _roi_grid(x, rois, pooled_h, pooled_w, spatial_scale, sampling=2, align=True):
    """Bilinear ROI align core: x NCHW, rois (R,5) [batch_idx,x1,y1,x2,y2]."""
    n, c, h, w = x.shape
    bidx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:] * spatial_scale
    off = 0.5 if align else 0.0
    x1, y1, x2, y2 = boxes[:, 0] - off, boxes[:, 1] - off, boxes[:, 2] - off, boxes[:, 3] - off
    bw = jnp.maximum(x2 - x1, 1.0) / pooled_w
    bh = jnp.maximum(y2 - y1, 1.0) / pooled_h
    ys = y1[:, None] + bh[:, None] * (jnp.arange(pooled_h * sampling) + 0.5) / sampling
    xs = x1[:, None] + bw[:, None] * (jnp.arange(pooled_w * sampling) + 0.5) / sampling

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = yy - y0
        wx = xx - x0
        va = img[:, y0[:, None], x0[None, :]]
        vb = img[:, y0[:, None], x1_[None, :]]
        vc = img[:, y1_[:, None], x0[None, :]]
        vd = img[:, y1_[:, None], x1_[None, :]]
        return (va * ((1 - wy)[:, None] * (1 - wx)[None, :]) +
                vb * ((1 - wy)[:, None] * wx[None, :]) +
                vc * (wy[:, None] * (1 - wx)[None, :]) +
                vd * (wy[:, None] * wx[None, :]))

    def per_roi(b, yy, xx):
        img = x[b]  # (C, H, W)
        vals = bilinear(img, yy, xx)  # (C, ph*s, pw*s)
        vals = vals.reshape(c, pooled_h, sampling, pooled_w, sampling)
        return vals.mean(axis=(2, 4))

    return jax.vmap(per_roi)(bidx, ys, xs)


@register("roi_align")
def roi_align(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    out = _roi_grid(x, rois, ctx.attr("pooled_height", 1), ctx.attr("pooled_width", 1),
                    ctx.attr("spatial_scale", 1.0), ctx.attr("sampling_ratio", 2) or 2)
    return {"Out": out}


@register("roi_pool")
def roi_pool(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    # Max-pool variant approximated with dense sampling + max
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    out = _roi_grid(x, rois, ph, pw, ctx.attr("spatial_scale", 1.0), sampling=2,
                    align=False)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, jnp.int64)}


@register("psroi_pool")
def psroi_pool(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    out_c = ctx.attr("output_channels")
    ph = ctx.attr("pooled_height")
    pw = ctx.attr("pooled_width")
    pooled = _roi_grid(x, rois, ph, pw, ctx.attr("spatial_scale", 1.0))
    r = pooled.shape[0]
    pooled = pooled.reshape(r, out_c, ph, pw, ph, pw)
    idx_h = jnp.arange(ph)
    idx_w = jnp.arange(pw)
    out = pooled[:, :, idx_h[:, None], idx_w[None, :], idx_h[:, None], idx_w[None, :]]
    return {"Out": out.reshape(r, out_c, ph, pw)}


@register("box_coder")
def box_coder(ctx):
    prior = ctx.in_("PriorBox")      # (M, 4)
    target = ctx.in_("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
    else:
        d = target  # (N, M, 4) or (M, 4)
        if d.ndim == 2:
            d = d[:, None, :]
        cx = pcx + d[..., 0] * pw
        cy = pcy + d[..., 1] * ph
        w = pw * jnp.exp(d[..., 2])
        h = ph * jnp.exp(d[..., 3])
        out = jnp.stack([cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1)
    return {"OutputBox": out}


@register("yolo_box")
def yolo_box(ctx):
    x = ctx.in_("X")  # (N, A*(5+C), H, W)
    img_size = ctx.in_("ImgSize")
    anchors = ctx.attr("anchors")
    class_num = ctx.attr("class_num")
    conf_thresh = ctx.attr("conf_thresh", 0.01)
    downsample = ctx.attr("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (w * downsample)
    bh = jnp.exp(x[:, :, 3]) * ah / (h * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imgh = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
    imgw = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
    boxes = jnp.stack([(bx - bw / 2) * imgw, (by - bh / 2) * imgh,
                       (bx + bw / 2) * imgw, (by + bh / 2) * imgh], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    probs = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(boxes.dtype)
    return {"Boxes": boxes * mask, "Scores": probs * mask}


@register("prior_box")
def prior_box(ctx):
    inp = ctx.in_("Input")  # (N, C, H, W) feature map
    image = ctx.in_("Image")
    min_sizes = ctx.attr("min_sizes")
    max_sizes = ctx.attr("max_sizes", []) or []
    ars = ctx.attr("aspect_ratios", [1.0])
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    flip = ctx.attr("flip", False)
    clip = ctx.attr("clip", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    full_ars = []
    for ar in ars:
        full_ars.append(ar)
        if flip and ar != 1.0:
            full_ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        for ar in full_ars:
            bw = ms * (ar ** 0.5) / 2.0
            bh = ms / (ar ** 0.5) / 2.0
            boxes.append((bw, bh))
        for Ms in max_sizes:
            s = (ms * Ms) ** 0.5 / 2.0
            boxes.append((s, s))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(cxg - bw) / img_w, (cyg - bh) / img_h,
                              (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1))
    priors = jnp.stack(out, axis=2)  # (H, W, num_priors, 4)
    if clip:
        priors = jnp.clip(priors, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), priors.shape)
    return {"Boxes": priors, "Variances": var}
