"""Detection ops (subset).

Parity: paddle/fluid/operators/detection/{roi_pool,roi_align,prior_box,
iou_similarity,box_coder,yolo_box}_op.* — enough for the detection demo
models; NMS runs as a host-side utility (paddle_tpu.layers.detection).
"""

import jax
import jax.numpy as jnp
import numpy as np

from . import register, DEVICE_INT
from .nn_ops import _pair


@register("iou_similarity")
def iou_similarity(ctx):
    """Parity: iou_similarity_op. box_normalized=False means inclusive
    pixel coordinates: +1 on every width/height (same convention as
    box_coder's unnormalized mode)."""
    x = ctx.in_("X")  # (N, 4) xmin,ymin,xmax,ymax
    y = ctx.in_("Y")  # (M, 4)
    one = 0.0 if ctx.attr("box_normalized", True) else 1.0
    area_x = (x[:, 2] - x[:, 0] + one) * (x[:, 3] - x[:, 1] + one)
    area_y = (y[:, 2] - y[:, 0] + one) * (y[:, 3] - y[:, 1] + one)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt + one, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / jnp.maximum(area_x[:, None] + area_y[None, :] - inter, 1e-10)}


def _roi_grid(x, rois, pooled_h, pooled_w, spatial_scale, sampling=2,
              half_pixel=False):
    """Bilinear ROI align core: x NCHW, rois (R,5) [batch_idx,x1,y1,x2,y2].

    half_pixel=False is the FLUID convention (roi_align_op.h:186-192:
    corners scale directly, no -0.5 offset, widths clamped to >=1 —
    torchvision's aligned=False); half_pixel=True is the later
    Detectron2/paddle-2.x aligned mode, kept for forward compat."""
    n, c, h, w = x.shape
    bidx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:] * spatial_scale
    off = 0.5 if half_pixel else 0.0
    x1, y1, x2, y2 = boxes[:, 0] - off, boxes[:, 1] - off, boxes[:, 2] - off, boxes[:, 3] - off
    bw = jnp.maximum(x2 - x1, 1.0) / pooled_w
    bh = jnp.maximum(y2 - y1, 1.0) / pooled_h
    ys = y1[:, None] + bh[:, None] * (jnp.arange(pooled_h * sampling) + 0.5) / sampling
    xs = x1[:, None] + bw[:, None] * (jnp.arange(pooled_w * sampling) + 0.5) / sampling

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = yy - y0
        wx = xx - x0
        va = img[:, y0[:, None], x0[None, :]]
        vb = img[:, y0[:, None], x1_[None, :]]
        vc = img[:, y1_[:, None], x0[None, :]]
        vd = img[:, y1_[:, None], x1_[None, :]]
        return (va * ((1 - wy)[:, None] * (1 - wx)[None, :]) +
                vb * ((1 - wy)[:, None] * wx[None, :]) +
                vc * (wy[:, None] * (1 - wx)[None, :]) +
                vd * (wy[:, None] * wx[None, :]))

    def per_roi(b, yy, xx):
        img = x[b]  # (C, H, W)
        vals = bilinear(img, yy, xx)  # (C, ph*s, pw*s)
        vals = vals.reshape(c, pooled_h, sampling, pooled_w, sampling)
        return vals.mean(axis=(2, 4))

    return jax.vmap(per_roi)(bidx, ys, xs)


@register("roi_align")
def roi_align(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    sr = ctx.attr("sampling_ratio", 2)
    sr = 2 if sr is None or sr <= 0 else sr   # fluid's -1 = "auto"
    out = _roi_grid(x, rois, ctx.attr("pooled_height", 1),
                    ctx.attr("pooled_width", 1),
                    ctx.attr("spatial_scale", 1.0), sr)
    return {"Out": out}


@register("roi_pool")
def roi_pool(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    # Max-pool variant approximated with dense sampling + max
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    out = _roi_grid(x, rois, ph, pw, ctx.attr("spatial_scale", 1.0),
                    sampling=2)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, DEVICE_INT)}


@register("psroi_pool")
def psroi_pool(ctx):
    """Parity: psroi_pool_op.h:84-135 — position-sensitive RoI AVERAGE
    pooling over integer bins: corners round (+1 on the far edge) then
    scale, widths clamp to >=0.1, bin (i, j) of category c averages
    input channel (c*ph + i)*pw + j over [floor(start), ceil(end)) cells
    (empty bins emit 0). TPU-native: the per-bin integer loops become
    row/column interval masks contracted in one einsum."""
    x = ctx.in_("X")                    # (N, C, H, W)
    rois = ctx.in_("ROIs")              # (R, 5) [batch, x1, y1, x2, y2]
    out_c = _to_int(ctx.attr("output_channels"))
    ph = _to_int(ctx.attr("pooled_height"))
    pw = _to_int(ctx.attr("pooled_width"))
    scale = ctx.attr("spatial_scale", 1.0)
    n, c, h, w = x.shape
    bidx = rois[:, 0].astype(jnp.int32)
    xs = _round_away(rois[:, 1]) * scale
    ys = _round_away(rois[:, 2]) * scale
    xe = (_round_away(rois[:, 3]) + 1.0) * scale
    ye = (_round_away(rois[:, 4]) + 1.0) * scale
    rw = jnp.maximum(xe - xs, 0.1)
    rh = jnp.maximum(ye - ys, 0.1)
    bh = rh / ph                        # (R,)
    bw = rw / pw

    def interval_mask(start, bsize, bins, size):
        # (R, bins, size) 0/1 mask of [floor(i*b+s), ceil((i+1)*b+s))
        i = jnp.arange(bins, dtype=x.dtype)
        lo = jnp.clip(jnp.floor(i[None] * bsize[:, None] + start[:, None]),
                      0, size)
        hi = jnp.clip(jnp.ceil((i[None] + 1) * bsize[:, None]
                               + start[:, None]), 0, size)
        cells = jnp.arange(size, dtype=x.dtype)
        return ((cells[None, None] >= lo[..., None])
                & (cells[None, None] < hi[..., None])).astype(x.dtype)

    rmask = interval_mask(ys, bh, ph, h)        # (R, ph, H)
    cmask = interval_mask(xs, bw, pw, w)        # (R, pw, W)
    xg = x[bidx].reshape(-1, out_c, ph, pw, h, w)
    sums = jnp.einsum("rcijhw,rih,rjw->rcij", xg, rmask, cmask)
    area = jnp.einsum("rih,rjw->rij", rmask, cmask)[:, None]
    return {"Out": jnp.where(area > 0, sums / jnp.maximum(area, 1.0), 0.0)}


@register("box_coder")
def box_coder(ctx):
    """Parity: box_coder_op.h. Encode: offsets (t-p)/pw scaled by
    1/variance; decode: cx = var*d*pw + pcx, w = pw*exp(var*d).
    box_normalized=False adds the reference's +1 to widths (corner
    pixels inclusive) and subtracts 1 back on decoded corners.
    PriorBoxVar input or the `variance` attr list supplies per-coord
    variances (both optional); decode `axis` picks which dim of the
    (N, M, 4) target the priors broadcast along."""
    prior = ctx.in_("PriorBox")      # (M, 4)
    target = ctx.in_("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    normalized = bool(ctx.attr("box_normalized", True))
    axis = ctx.attr("axis", 0)
    one = 0.0 if normalized else 1.0
    var = None
    if ctx.has_in("PriorBoxVar"):
        var = ctx.in_("PriorBoxVar")                 # (M, 4)
    else:
        vattr = ctx.attr("variance", None)
        if vattr:
            var = jnp.asarray(vattr, prior.dtype).reshape(1, 4)
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        # (N, M, 4): every target against every prior (reference
        # EncodeCenterSize loops n x m)
        out = jnp.stack(
            [(tcx[:, None] - pcx[None, :]) / pw[None, :],
             (tcy[:, None] - pcy[None, :]) / ph[None, :],
             jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
             jnp.log(jnp.abs(th[:, None] / ph[None, :]))], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return {"OutputBox": out}
    # decode_center_size
    d = target  # (N, M, 4); a 2D (M, 4) target row-matches its priors
    squeeze_2d = d.ndim == 2
    if squeeze_2d:
        # local extension (the reference only takes rank-3 here): one
        # offset per prior -> (1, M, 4) so axis=0 pairs row i <-> prior i
        d = d[None, :, :]
        axis = 0
    shape = [1, 1, 4]
    # reference DecodeCenterSize: axis==0 indexes priors by the COLUMN
    # (priors vary along target dim 1, broadcast over dim 0); axis==1
    # indexes by the row
    pshape = [1, 1]
    pshape[1 - axis] = -1
    pw_b = pw.reshape(pshape)
    ph_b = ph.reshape(pshape)
    pcx_b = pcx.reshape(pshape)
    pcy_b = pcy.reshape(pshape)
    if var is not None:
        if var.shape[0] == 1:
            v = var.reshape(shape)
        else:
            vshape = pshape + [4]
            v = var.reshape(vshape)
        d = d * v
    cx = pcx_b + d[..., 0] * pw_b
    cy = pcy_b + d[..., 1] * ph_b
    w = pw_b * jnp.exp(d[..., 2])
    h = ph_b * jnp.exp(d[..., 3])
    out = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                     cx + 0.5 * w - one, cy + 0.5 * h - one], axis=-1)
    if squeeze_2d:
        out = out[0]
    return {"OutputBox": out}


@register("yolo_box")
def yolo_box(ctx):
    x = ctx.in_("X")  # (N, A*(5+C), H, W)
    img_size = ctx.in_("ImgSize")
    anchors = ctx.attr("anchors")
    class_num = ctx.attr("class_num")
    conf_thresh = ctx.attr("conf_thresh", 0.01)
    downsample = ctx.attr("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (w * downsample)
    bh = jnp.exp(x[:, :, 3]) * ah / (h * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imgh = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
    imgw = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
    boxes = jnp.stack([(bx - bw / 2) * imgw, (by - bh / 2) * imgh,
                       (bx + bw / 2) * imgw, (by + bh / 2) * imgh], axis=-1)
    if ctx.attr("clip_bbox", True):
        # reference yolo_box_op default: clamp to the image rectangle
        lim = jnp.stack([imgw, imgh, imgw, imgh],
                        axis=-1).reshape(n, 1, 1, 1, 4) - 1.0
        boxes = jnp.clip(boxes, 0.0, lim)
    boxes = boxes.reshape(n, -1, 4)
    probs = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(boxes.dtype)
    return {"Boxes": boxes * mask, "Scores": probs * mask}


def expand_aspect_ratios(ars, flip):
    """Parity: prior_box_op.h:28 ExpandAspectRatios — 1.0 always leads,
    near-duplicates (eps 1e-6) are dropped, flip appends 1/ar."""
    out = [1.0]
    for ar in ars:
        if any(abs(ar - e) < 1e-6 for e in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


@register("prior_box")
def prior_box(ctx):
    """Parity: paddle/fluid/operators/detection/prior_box_op.h:53-170.
    Per cell, per min_sizes[s]: all expanded aspect ratios then ONE
    sqrt(min_sizes[s]*max_sizes[s]) square box (paired by index s, not a
    cross product); min_max_aspect_ratios_order flips that order to
    min, max, then non-1 ratios."""
    inp = ctx.in_("Input")  # (N, C, H, W) feature map
    image = ctx.in_("Image")
    min_sizes = ctx.attr("min_sizes")
    max_sizes = ctx.attr("max_sizes", []) or []
    ars = ctx.attr("aspect_ratios", [1.0])
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    flip = ctx.attr("flip", False)
    clip = ctx.attr("clip", False)
    mm_order = ctx.attr("min_max_aspect_ratios_order", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    # prior_box_op.h:85 — EITHER step being zero discards BOTH and falls
    # back to the image/feature ratio.
    if step_w == 0 or step_h == 0:
        sw, sh = img_w / w, img_h / h
    else:
        sw, sh = step_w, step_h
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            "prior_box: max_sizes pairs with min_sizes by index "
            f"(got {len(min_sizes)} min_sizes, {len(max_sizes)} max_sizes)")
    full_ars = expand_aspect_ratios(ars, flip)
    boxes = []
    for s, ms in enumerate(min_sizes):
        ratio_boxes = [(ms * ar ** 0.5 / 2.0, ms / ar ** 0.5 / 2.0)
                       for ar in full_ars]
        if mm_order:
            boxes.append((ms / 2.0, ms / 2.0))
            if max_sizes:
                sq = (ms * max_sizes[s]) ** 0.5 / 2.0
                boxes.append((sq, sq))
            boxes += [b for ar, b in zip(full_ars, ratio_boxes)
                      if abs(ar - 1.0) >= 1e-6]
        else:
            boxes += ratio_boxes
            if max_sizes:
                sq = (ms * max_sizes[s]) ** 0.5 / 2.0
                boxes.append((sq, sq))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(cxg - bw) / img_w, (cyg - bh) / img_h,
                              (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1))
    priors = jnp.stack(out, axis=2)  # (H, W, num_priors, 4)
    if clip:
        priors = jnp.clip(priors, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), priors.shape)
    return {"Boxes": priors, "Variances": var}


@register("density_prior_box")
def density_prior_box(ctx):
    """Parity: paddle/fluid/operators/detection/density_prior_box_op.cc —
    dense grids of fixed-size priors per cell (PyramidBox-style)."""
    inp = ctx.in_("Input")
    image = ctx.in_("Image")
    fixed_sizes = ctx.attr("fixed_sizes", [])
    fixed_ratios = ctx.attr("fixed_ratios", [1.0])
    densities = ctx.attr("densities", [1])
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    clip = ctx.attr("clip", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    if step_w == 0 or step_h == 0:  # either zero discards both (op.h:66)
        sw, sh = img_w / w, img_h / h
    else:
        sw, sh = step_w, step_h
    if len(densities) != len(fixed_sizes):
        raise ValueError(
            "density_prior_box: densities pairs with fixed_sizes by index "
            f"(got {len(fixed_sizes)} fixed_sizes, {len(densities)} densities)")
    # density_prior_box_op.h:69-101: a single INTEGER step_average drives
    # both axes, shift is the integer quotient step_average // density,
    # and every coordinate is clamped to [0, 1] inline in the generation
    # loop regardless of `clip` (which only adds a redundant second pass).
    step_average = int((sw + sh) * 0.5)
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = []
    for size, density in zip(fixed_sizes, densities):
        shift = step_average // density
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            for di in range(density):
                for dj in range(density):
                    ccx = cxg - step_average / 2.0 + shift / 2.0 + dj * shift
                    ccy = cyg - step_average / 2.0 + shift / 2.0 + di * shift
                    out.append(jnp.stack(
                        [jnp.maximum((ccx - bw / 2.0) / img_w, 0.0),
                         jnp.maximum((ccy - bh / 2.0) / img_h, 0.0),
                         jnp.minimum((ccx + bw / 2.0) / img_w, 1.0),
                         jnp.minimum((ccy + bh / 2.0) / img_h, 1.0)],
                        axis=-1))
    priors = jnp.stack(out, axis=2)  # (H, W, num_priors, 4)
    if clip:  # redundant second clamp pass, kept for reference parity
        priors = jnp.clip(priors, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), priors.shape)
    if ctx.attr("flatten_to_2d", False):
        priors = priors.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return {"Boxes": priors, "Variances": var}


def _suppress_sorted(cand, top_scores, score_thresh, nms_thresh):
    """Greedy IoU suppression over score-DESCENDING candidates (K, 4).
    Returns the keep mask. O(K^2) IoU + a fori_loop sweep — regular XLA
    ops, no host round-trip."""
    k = cand.shape[0]
    x1, y1, x2, y2 = cand[:, 0], cand[:, 1], cand[:, 2], cand[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, keep):
        sup = (iou[i] > nms_thresh) & keep[i] & (jnp.arange(k) > i)
        return keep & ~sup

    keep = top_scores > score_thresh
    return jax.lax.fori_loop(0, k, body, keep)


def _nms_single(boxes, scores, score_thresh, nms_thresh, top_k):
    """Static-shape class-wise NMS core: returns (keep_mask, order,
    sorted_scores) over the top_k candidates."""
    k = min(top_k, scores.shape[0])
    top_scores, order = jax.lax.top_k(scores, k)
    cand = boxes[order]  # (K, 4)
    keep = _suppress_sorted(cand, top_scores, score_thresh, nms_thresh)
    return keep, order, top_scores


def _multiclass_nms_arrays(bboxes, scores, score_thresh, nms_thresh,
                           nms_top_k, keep_top_k, background):
    """Shared multiclass NMS core: bboxes (N, M, 4), scores (N, C, M)
    -> (N, keep_top_k, 6) [class, score, x1, y1, x2, y2] padded -1."""
    n, c, m = scores.shape

    def per_image(boxes_i, scores_i):
        all_scores, all_cls, all_boxes = [], [], []
        for cls in range(c):
            if cls == background:
                continue
            keep, order, top_scores = _nms_single(
                boxes_i, scores_i[cls], score_thresh, nms_thresh, nms_top_k)
            kept_scores = jnp.where(keep, top_scores, -1.0)
            all_scores.append(kept_scores)
            all_cls.append(jnp.full_like(kept_scores, cls))
            all_boxes.append(boxes_i[order])
        cat_scores = jnp.concatenate(all_scores)
        cat_cls = jnp.concatenate(all_cls)
        cat_boxes = jnp.concatenate(all_boxes, axis=0)
        kk = min(keep_top_k, cat_scores.shape[0])
        final_scores, idx = jax.lax.top_k(cat_scores, kk)
        out = jnp.concatenate(
            [cat_cls[idx][:, None], final_scores[:, None], cat_boxes[idx]],
            axis=-1)
        out = jnp.where(final_scores[:, None] > 0, out, -1.0)
        if kk < keep_top_k:
            out = jnp.pad(out, ((0, keep_top_k - kk), (0, 0)),
                          constant_values=-1.0)
        return out

    return jax.vmap(per_image)(bboxes, scores)


@register("multiclass_nms")
def multiclass_nms(ctx):
    """Parity: paddle/fluid/operators/detection/multiclass_nms_op.cc.
    Static-shape output: (N, keep_top_k, 6) [class, score, x1, y1, x2, y2]
    padded with -1 rows (the TPU replacement for the reference's LoD
    variable-length output)."""
    return {"Out": _multiclass_nms_arrays(
        ctx.in_("BBoxes"), ctx.in_("Scores"),
        ctx.attr("score_threshold", 0.01), ctx.attr("nms_threshold", 0.3),
        ctx.attr("nms_top_k", 64), ctx.attr("keep_top_k", 100),
        ctx.attr("background_label", 0))}


@register("ssd_loss")
def ssd_loss(ctx):
    """Parity: fluid.layers.ssd_loss (detection/target_assign + conf/loc
    loss). Simplified static-shape variant: anchors matched to the best gt
    box by IoU; conf = softmax CE, loc = smooth-l1 on matched anchors."""
    loc = ctx.in_("Location")        # (N, M, 4) predicted offsets
    conf = ctx.in_("Confidence")     # (N, M, C) logits
    gt_box = ctx.in_("GtBox")        # (N, G, 4)
    gt_label = ctx.in_("GtLabel")    # (N, G) or (N, G, 1)
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    prior = ctx.in_("PriorBox")      # (M, 4)
    overlap_thresh = ctx.attr("overlap_threshold", 0.5)
    neg_ratio = ctx.attr("neg_pos_ratio", 3.0)
    background = int(ctx.attr("background_label", 0))

    def per_image(loc_i, conf_i, gt_b, gt_l):
        iou = _iou_matrix(prior, gt_b)        # (M, G)
        best_iou = iou.max(axis=1)
        best_gt = iou.argmax(axis=1)
        pos = best_iou > overlap_thresh       # (M,)
        target_label = jnp.where(pos, gt_l[best_gt], background)
        # localization target: encode matched gt vs prior (center-size)
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = (prior[:, 0] + prior[:, 2]) / 2
        pcy = (prior[:, 1] + prior[:, 3]) / 2
        g = gt_b[best_gt]
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-10)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-10)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        t = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                       jnp.log(gw / pw), jnp.log(gh / ph)], axis=-1)
        diff = jnp.abs(loc_i - t)
        loc_l = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(-1)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        conf_l = -jnp.take_along_axis(logp, target_label[:, None].astype(jnp.int32),
                                      axis=-1)[:, 0]
        # hard negative mining: top (neg_ratio * num_pos) negatives by loss
        num_pos = jnp.maximum(pos.sum(), 1)
        neg_loss = jnp.where(pos, -jnp.inf, conf_l)
        rank = jnp.argsort(jnp.argsort(-neg_loss))
        neg = rank < (neg_ratio * num_pos)
        total = (jnp.where(pos, loc_l + conf_l, 0.0).sum() +
                 jnp.where(neg, conf_l, 0.0).sum())
        return total / num_pos

    return {"Out": jax.vmap(per_image)(loc, conf, gt_box, gt_label)}


def _iou_matrix(a, b):
    """(M,4) x (G,4) xyxy -> (M,G) IoU."""
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    aa = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    ab = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    return inter / jnp.maximum(aa[:, None] + ab[None, :] - inter, 1e-10)


@register("anchor_generator")
def anchor_generator(ctx):
    """Dense anchors per feature-map cell (reference: anchor_generator_op,
    Faster R-CNN)."""
    feat = ctx.in_("Input")            # (N, C, H, W)
    sizes = ctx.attr("anchor_sizes", [64.0, 128.0, 256.0, 512.0])
    ratios = ctx.attr("aspect_ratios", [0.5, 1.0, 2.0])
    stride = ctx.attr("stride", [16.0, 16.0])
    offset = ctx.attr("offset", 0.5)
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    # Reference kernel semantics (anchor_generator_op.h): centers at
    # i*stride + offset*(stride-1); per-anchor w/h from the rounded
    # stride-area base (area/ar, ar = h/w) scaled by size/stride; corners
    # pixel-inclusive (+/- 0.5*(wh-1)); anchor channel order iterates
    # RATIOS outer, SIZES inner.
    import math
    sw, sh = float(stride[0]), float(stride[1])
    cx = jnp.arange(w) * sw + offset * (sw - 1.0)
    cy = jnp.arange(h) * sh + offset * (sh - 1.0)
    whs = []
    for r in ratios:
        base_w = round(math.sqrt(sw * sh / r))
        base_h = round(base_w * r)
        for s in sizes:
            whs.append((s / sw * base_w, s / sh * base_h))
    whs = jnp.asarray(whs)             # (A, 2) ratios-outer/sizes-inner
    gx, gy = jnp.meshgrid(cx, cy)      # (H, W)
    centers = jnp.stack([gx, gy], -1)[:, :, None, :]          # (H, W, 1, 2)
    half = (whs[None, None] - 1.0) / 2                         # (1, 1, A, 2)
    boxes = jnp.concatenate([centers - half, centers + half], -1)
    var = jnp.broadcast_to(jnp.asarray(variances), boxes.shape)
    return {"Anchors": boxes, "Variances": var}


@register("bipartite_match")
def bipartite_match(ctx):
    """Greedy bipartite matching of rows (gt) to cols (priors) by max
    similarity (reference: bipartite_match_op). Static-shape greedy loop
    over the (small) gt dimension."""
    sim = ctx.in_("DistMat")           # (B, G, M) or (G, M)
    squeeze = sim.ndim == 2
    if squeeze:
        sim = sim[None]

    def one(s):
        g, m = s.shape
        def body(i, carry):
            match_idx, match_dist, s_cur = carry
            flat = jnp.argmax(s_cur)
            gi, mi = flat // m, flat % m
            best = s_cur[gi, mi]
            valid = best > -1e9
            match_idx = jnp.where(valid, match_idx.at[gi].set(mi), match_idx)
            match_dist = jnp.where(valid, match_dist.at[gi].set(best), match_dist)
            s_cur = s_cur.at[gi, :].set(-1e10)
            s_cur = s_cur.at[:, mi].set(-1e10)
            return match_idx, match_dist, s_cur

        init = (jnp.full((g,), -1, jnp.int32), jnp.zeros((g,), s.dtype), s)
        mi, md, _ = jax.lax.fori_loop(0, min(g, m), body, init)
        # column-major outputs like the reference: (M,) row match per prior.
        # Unmatched rows scatter into a dummy column m, then dropped — they
        # must not clobber a real match at column 0.
        valid = mi >= 0
        tgt = jnp.where(valid, mi, m)
        col_idx = jnp.full((m + 1,), -1, jnp.int32).at[tgt].set(
            jnp.where(valid, jnp.arange(g), -1))[:m]
        col_dist = jnp.zeros((m + 1,), s.dtype).at[tgt].set(
            jnp.where(valid, md, 0.0))[:m]
        if match_type == "per_prediction":
            # reference bipartite_match_op ArgMaxMatch extension: every
            # still-unmatched column joins its argmax row when the
            # similarity clears dist_threshold (SSD's matching mode)
            best_row = jnp.argmax(s, axis=0)
            best_val = jnp.max(s, axis=0)
            extra = (col_idx < 0) & (best_val >= dist_threshold)
            col_idx = jnp.where(extra, best_row.astype(jnp.int32), col_idx)
            col_dist = jnp.where(extra, best_val, col_dist)
        return col_idx, col_dist

    match_type = ctx.attr("match_type", "bipartite")
    dist_threshold = ctx.attr("dist_threshold", 0.5)
    idx, dist = jax.vmap(one)(sim)
    if squeeze:
        idx, dist = idx[0], dist[0]
    return {"ColToRowMatchIndices": idx, "ColToRowMatchDist": dist}


@register("target_assign")
def target_assign(ctx):
    """Assign per-prior targets from matched gt rows (reference:
    target_assign_op)."""
    x = ctx.in_("X")                   # (B, G, K) gt attributes
    match = ctx.in_("MatchIndices")    # (B, M) gt row per prior, -1 none
    mismatch_value = ctx.attr("mismatch_value", 0)
    if mismatch_value is None:     # reference default: fill with 0
        mismatch_value = 0
    safe = jnp.maximum(match, 0)
    g = jnp.take_along_axis(x, safe[..., None], axis=1)
    neg = match < 0
    out = jnp.where(neg[..., None], mismatch_value, g)
    wt = jnp.where(neg, 0.0, 1.0)[..., None]
    return {"Out": out, "OutWeight": wt}


@register("box_clip")
def box_clip(ctx):
    boxes = ctx.in_("Input")           # (B, M, 4) or (M, 4) xyxy
    im_info = ctx.in_("ImInfo")        # (B, 3) h, w, scale
    h = im_info[:, 0] - 1.0            # (B,)
    w = im_info[:, 1] - 1.0
    if boxes.ndim == 3:                # broadcast per-image limits over M
        h, w = h[:, None], w[:, None]
    else:                              # single image: scalar limits
        h, w = h[0], w[0]
    out = jnp.stack([
        jnp.clip(boxes[..., 0], 0.0, w),
        jnp.clip(boxes[..., 1], 0.0, h),
        jnp.clip(boxes[..., 2], 0.0, w),
        jnp.clip(boxes[..., 3], 0.0, h),
    ], axis=-1)
    return {"Output": out, "Out": out}


@register("polygon_box_transform")
def polygon_box_transform(ctx):
    """Quad offset map -> absolute coords (reference:
    polygon_box_transform_op, OCR EAST)."""
    x = ctx.in_("Input")               # (N, 8, H, W) offsets
    n, c, h, w = x.shape
    gx = jnp.tile(jnp.arange(w, dtype=x.dtype), (h, 1)) * 4.0
    gy = jnp.tile(jnp.arange(h, dtype=x.dtype)[:, None], (1, w)) * 4.0
    base = jnp.stack([gx, gy] * (c // 2))      # (C, H, W) alternating x/y
    return {"Output": base[None] - x, "Out": base[None] - x}


@register("yolov3_loss")
def yolov3_loss(ctx):
    """YOLOv3 training loss (reference: yolov3_loss_op): coord (sigmoid xy +
    raw wh) vs anchor-encoded gt, objectness BCE with ignore threshold,
    class BCE. Static shapes: gt padded to max boxes."""
    x = ctx.in_("X")                   # (N, A*(5+C), H, W)
    gt_box = ctx.in_("GTBox")          # (N, G, 4) cx,cy,w,h normalized
    gt_label = ctx.in_("GTLabel")      # (N, G)
    anchors = ctx.attr("anchors")      # flat [w0,h0,w1,h1,...]
    mask = ctx.attr("anchor_mask")
    num_classes = ctx.attr("class_num")
    ignore_thresh = ctx.attr("ignore_thresh", 0.7)
    downsample = ctx.attr("downsample_ratio", 32)
    use_label_smooth = bool(ctx.attr("use_label_smooth", True))
    if ctx.in_("GTScore") is not None:
        import warnings
        warnings.warn(
            "yolov3_loss: gt_score weighting is not supported and will "
            "be ignored", RuntimeWarning, stacklevel=2)
    n, _, h, w = x.shape
    na = len(mask)
    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    amask = jnp.asarray(mask, jnp.int32)
    anc = all_anchors[amask]           # (na, 2) in input pixels
    in_h, in_w = h * downsample, w * downsample
    x = x.reshape(n, na, 5 + num_classes, h, w)
    px = jax.nn.sigmoid(x[:, :, 0])
    py = jax.nn.sigmoid(x[:, :, 1])
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]

    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    gw = gt_box[..., 2]
    valid = gw > 1e-6                  # (N, G)
    # responsible cell + anchor per gt
    cx = gt_box[..., 0] * w
    cy = gt_box[..., 1] * h
    ci = jnp.clip(cx.astype(jnp.int32), 0, w - 1)
    cj = jnp.clip(cy.astype(jnp.int32), 0, h - 1)
    bw = gt_box[..., 2] * in_w
    bh = gt_box[..., 3] * in_h
    # best anchor by wh IoU (over the FULL anchor set, reference semantics)
    inter = (jnp.minimum(bw[..., None], all_anchors[:, 0]) *
             jnp.minimum(bh[..., None], all_anchors[:, 1]))
    union = bw[..., None] * bh[..., None] + \
        all_anchors[:, 0] * all_anchors[:, 1] - inter
    best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # (N, G)
    # position of best anchor within this level's mask (-1 if not here)
    in_mask = (best_a[..., None] == amask)
    a_idx = jnp.argmax(in_mask, -1)
    resp = valid & in_mask.any(-1)

    tx = cx - ci
    ty = cy - cj
    tw = jnp.log(jnp.maximum(bw / jnp.maximum(anc[a_idx, 0], 1e-6), 1e-9))
    th = jnp.log(jnp.maximum(bh / jnp.maximum(anc[a_idx, 1], 1e-6), 1e-9))
    scale = 2.0 - gt_box[..., 2] * gt_box[..., 3]

    def gather_pred(p):
        # p: (N, na, H, W) -> per gt (N, G)
        flat = p.reshape(n, -1)
        idx = (a_idx * h + cj) * w + ci
        return jnp.take_along_axis(flat, idx, -1)

    l_x = (gather_pred(px) - tx) ** 2 * scale
    l_y = (gather_pred(py) - ty) ** 2 * scale
    l_w = (gather_pred(pw) - tw) ** 2 * scale * 0.5
    l_h = (gather_pred(ph) - th) ** 2 * scale * 0.5
    coord = jnp.where(resp, l_x + l_y + l_w + l_h, 0.0).sum(-1)

    # objectness target map
    obj_t = jnp.zeros((n, na * h * w))
    idx = (a_idx * h + cj) * w + ci
    obj_t = jax.vmap(lambda o, i, r: o.at[jnp.where(r, i, 0)].max(
        jnp.where(r, 1.0, 0.0)))(obj_t, idx, resp)
    obj_t = obj_t.reshape(n, na, h, w)
    # ignore threshold (reference semantics): decode every predicted box
    # and exclude non-responsible predictions whose best IoU against any
    # gt exceeds ignore_thresh from the objectness BCE.
    gx = jnp.arange(w, dtype=x.dtype)
    gy = jnp.arange(h, dtype=x.dtype)
    bx_p = (px + gx[None, None, None, :]) / w                 # (N,na,H,W)
    by_p = (py + gy[None, None, :, None]) / h
    bw_p = jnp.exp(jnp.clip(pw, -10, 10)) * anc[None, :, 0, None, None] / in_w
    bh_p = jnp.exp(jnp.clip(ph, -10, 10)) * anc[None, :, 1, None, None] / in_h
    pred = jnp.stack([bx_p, by_p, bw_p, bh_p], -1).reshape(n, -1, 4)

    def _cxcywh_iou(p, g):
        # p: (M,4), g: (G,4) normalized cx,cy,w,h
        px1, py1 = p[:, 0] - p[:, 2] / 2, p[:, 1] - p[:, 3] / 2
        px2, py2 = p[:, 0] + p[:, 2] / 2, p[:, 1] + p[:, 3] / 2
        gx1, gy1 = g[:, 0] - g[:, 2] / 2, g[:, 1] - g[:, 3] / 2
        gx2, gy2 = g[:, 0] + g[:, 2] / 2, g[:, 1] + g[:, 3] / 2
        ix = jnp.maximum(jnp.minimum(px2[:, None], gx2[None]) -
                         jnp.maximum(px1[:, None], gx1[None]), 0)
        iy = jnp.maximum(jnp.minimum(py2[:, None], gy2[None]) -
                         jnp.maximum(py1[:, None], gy1[None]), 0)
        inter = ix * iy
        ap = jnp.maximum(p[:, 2] * p[:, 3], 0)
        ag = jnp.maximum(g[:, 2] * g[:, 3], 0)
        return inter / jnp.maximum(ap[:, None] + ag[None] - inter, 1e-10)

    iou_pg = jax.vmap(_cxcywh_iou)(pred, gt_box)              # (N,M,G)
    iou_pg = jnp.where(valid[:, None, :], iou_pg, 0.0)
    best_iou = iou_pg.max(-1).reshape(n, na, h, w)
    obj_t_flat = obj_t
    ignore = (best_iou > ignore_thresh) & (obj_t_flat < 0.5)
    pobj_f = pobj
    bce_obj = jnp.maximum(pobj_f, 0) - pobj_f * obj_t + \
        jnp.log1p(jnp.exp(-jnp.abs(pobj_f)))
    bce_obj = jnp.where(ignore, 0.0, bce_obj)
    obj_loss = bce_obj.reshape(n, -1).sum(-1)

    tcls = jax.nn.one_hot(gt_label, num_classes)
    if use_label_smooth:
        # reference yolov3_loss_op.h:285: sw = min(1/C, 1/40);
        # positives 1-sw, negatives sw (the fluid DEFAULT is smoothing ON)
        sw = min(1.0 / num_classes, 1.0 / 40.0)
        tcls = tcls * (1.0 - sw) + (1.0 - tcls) * sw
    pcls_flat = pcls.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w,
                                                      num_classes)
    pcls_g = jnp.take_along_axis(pcls_flat, idx[..., None], axis=1)
    bce_cls = jnp.maximum(pcls_g, 0) - pcls_g * tcls + \
        jnp.log1p(jnp.exp(-jnp.abs(pcls_g)))
    cls_loss = jnp.where(resp[..., None], bce_cls, 0.0).sum((-1, -2))

    return {"Loss": coord + obj_loss + cls_loss}


@register("distribute_fpn_proposals")
def distribute_fpn_proposals(ctx):
    """Assign each RoI to an FPN level by scale (reference:
    distribute_fpn_proposals_op). Static shapes: every level output has the
    full roi count with a validity mask encoded as zero rois."""
    rois = ctx.in_("FpnRois")          # (R, 4)
    min_level = ctx.attr("min_level", 2)
    max_level = ctx.attr("max_level", 5)
    refer_level = ctx.attr("refer_level", 4)
    refer_scale = ctx.attr("refer_scale", 224)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    # reference area is PIXEL-INCLUSIVE ((w+1)*(h+1), BBoxArea with
    # normalized=false) and 0 for degenerate boxes
    # (distribute_fpn_proposals_op.h:85, bbox_util.h:33) — raw w*h
    # routed boundary boxes one level low
    area = jnp.where((w < 0) | (h < 0), 0.0, (w + 1.0) * (h + 1.0))
    scale = jnp.sqrt(area)
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs, idxs = [], []
    for L in range(min_level, max_level + 1):
        m = (lvl == L)[:, None]
        outs.append(jnp.where(m, rois, 0.0))
        idxs.append(m[:, 0].astype(jnp.int32))
    order = jnp.argsort(lvl, stable=True).astype(jnp.int32)
    return {"MultiFpnRois": outs, "RestoreIndex": order[:, None],
            "MultiLevelRoIsNum": [i.sum()[None] for i in idxs]}


@register("collect_fpn_proposals")
def collect_fpn_proposals(ctx):
    """Merge per-level RoIs back, keep top-N by score (reference:
    collect_fpn_proposals_op)."""
    rois = ctx.in_list("MultiLevelRois")
    scores = ctx.in_list("MultiLevelScores")
    post_nms_topn = ctx.attr("post_nms_topN", 100)
    allr = jnp.concatenate(rois, 0)
    alls = jnp.concatenate([s.reshape(-1) for s in scores], 0)
    k = min(post_nms_topn, alls.shape[0])
    _, idx = jax.lax.top_k(alls, k)
    return {"FpnRois": allr[idx], "RoisNum": jnp.asarray([k], jnp.int32)}


def _round_away(x):
    """std::round parity: half rounds AWAY from zero (numpy/jnp round is
    half-to-even, which lands .5 coords one pixel off)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


@register("deformable_psroi_pooling", "deformable_roi_pooling")
def deformable_roi_pooling(ctx):
    """Parity: deformable_psroi_pooling_op.h:57-153 — position-sensitive
    RoI bins with learned per-part offsets. Bin (i,j) of output channel
    ctop averages sample_per_part^2 bilinear samples of input channel
    (ctop*group_h + gh)*group_w + gw; samples outside [-0.5, dim-0.5]
    are skipped (count-normalised); RoI corners round, the far edge gets
    +1, and both shift by -0.5 after scaling. RoIs: (R, 5) with a batch
    index leading, or (R, 4) = all batch 0 (the reference carries batch
    ids in LoD, which is host-side metadata here)."""
    x = ctx.in_("Input")               # (N, C, H, W)
    rois = ctx.in_("ROIs")
    trans = ctx.in_("Trans") if ctx.has_in("Trans") else None
    no_trans = bool(ctx.attr("no_trans", trans is None)) or trans is None
    scale = ctx.attr("spatial_scale", 1.0)
    gh_, gw_ = _pair(ctx.attr("group_size", [1, 1]))
    ph = _to_int(ctx.attr("pooled_height", 1))
    pw = _to_int(ctx.attr("pooled_width", 1))
    part_h, part_w = _pair(ctx.attr("part_size") or [ph, pw])
    spp = _to_int(ctx.attr("sample_per_part", 1))
    trans_std = ctx.attr("trans_std", 0.1)
    n, c, h, w = x.shape
    out_dim = _to_int(ctx.attr("output_dim", c // (gh_ * gw_)))
    if out_dim * gh_ * gw_ > c:
        raise ValueError(
            "deformable_psroi_pooling: the PS channel map needs "
            f"output_dim*group_h*group_w <= C (got {out_dim}*{gh_}*{gw_} "
            f"> {c}); a clipped gather would silently read channel {c - 1}")
    if rois.shape[1] == 5:
        bidx, boxes = rois[:, 0].astype(jnp.int32), rois[:, 1:]
    else:
        bidx, boxes = jnp.zeros(rois.shape[0], jnp.int32), rois
    r = boxes.shape[0]
    start_w = _round_away(boxes[:, 0]) * scale - 0.5
    start_h = _round_away(boxes[:, 1]) * scale - 0.5
    roi_w = jnp.maximum((_round_away(boxes[:, 2]) + 1.0) * scale - 0.5 - start_w, 0.1)
    roi_h = jnp.maximum((_round_away(boxes[:, 3]) + 1.0) * scale - 0.5 - start_h, 0.1)
    bin_w, bin_h = roi_w / pw, roi_h / ph
    sub_w, sub_h = bin_w / spp, bin_h / spp

    # static per-bin lookup tables (pooled dims are compile-time)
    part_hi = np.floor(np.arange(ph) / ph * part_h).astype(np.int32)
    part_wi = np.floor(np.arange(pw) / pw * part_w).astype(np.int32)
    ghi = np.clip(np.floor(np.arange(ph) * gh_ / ph), 0, gh_ - 1).astype(np.int32)
    gwi = np.clip(np.floor(np.arange(pw) * gw_ / pw), 0, gw_ - 1).astype(np.int32)
    num_classes = 1 if no_trans else max(int(trans.shape[1]) // 2, 1)
    ch_each = max(out_dim // num_classes, 1)
    ctops = np.arange(out_dim)
    class_ids = np.minimum(ctops // ch_each, num_classes - 1)
    cmap = ((ctops[:, None, None] * gh_ + ghi[None, :, None]) * gw_
            + gwi[None, None, :])                     # (out_dim, ph, pw)

    if no_trans:
        tx = ty = jnp.zeros((r, num_classes, ph, pw), x.dtype)
    else:
        tgrid = trans[:, :, part_hi][:, :, :, part_wi]  # (R, 2nc, ph, pw)
        tv = tgrid.reshape(r, num_classes, 2, ph, pw) * trans_std
        tx, ty = tv[:, :, 0], tv[:, :, 1]

    # bin start per (R, class, ph, pw), then broadcast classes -> ctop
    jj = jnp.arange(pw, dtype=x.dtype)
    ii = jnp.arange(ph, dtype=x.dtype)
    wstart = (jj[None, None, None, :] * bin_w[:, None, None, None]
              + start_w[:, None, None, None] + tx * roi_w[:, None, None, None])
    hstart = (ii[None, None, :, None] * bin_h[:, None, None, None]
              + start_h[:, None, None, None] + ty * roi_h[:, None, None, None])
    wstart = wstart[:, class_ids]                     # (R, out_dim, ph, pw)
    hstart = hstart[:, class_ids]

    def per_roi(b, ws, hs, sw_, sh_):
        img = x[b]                                    # (C, H, W)
        acc = jnp.zeros((out_dim, ph, pw), x.dtype)
        cnt = jnp.zeros((out_dim, ph, pw), x.dtype)
        for ihs in range(spp):
            for iws in range(spp):
                wc = ws + iws * sw_
                hc = hs + ihs * sh_
                ok = ((wc >= -0.5) & (wc <= w - 0.5)
                      & (hc >= -0.5) & (hc <= h - 0.5))
                wcl = jnp.clip(wc, 0.0, w - 1.0)
                hcl = jnp.clip(hc, 0.0, h - 1.0)
                x1i = jnp.floor(wcl).astype(jnp.int32)
                x2i = jnp.ceil(wcl).astype(jnp.int32)
                y1i = jnp.floor(hcl).astype(jnp.int32)
                y2i = jnp.ceil(hcl).astype(jnp.int32)
                dx_ = wcl - x1i
                dy_ = hcl - y1i
                v = (img[cmap, y1i, x1i] * (1 - dx_) * (1 - dy_)
                     + img[cmap, y2i, x1i] * (1 - dx_) * dy_
                     + img[cmap, y1i, x2i] * dx_ * (1 - dy_)
                     + img[cmap, y2i, x2i] * dx_ * dy_)
                acc = acc + jnp.where(ok, v, 0.0)
                cnt = cnt + ok.astype(x.dtype)
        out = jnp.where(cnt > 0, acc / jnp.maximum(cnt, 1.0), 0.0)
        return out, cnt

    out, cnt = jax.vmap(per_roi)(
        bidx, wstart, hstart,
        jnp.broadcast_to(sub_w[:, None, None, None], wstart.shape),
        jnp.broadcast_to(sub_h[:, None, None, None], hstart.shape))
    return {"Output": out, "Out": out, "TopCount": cnt}


def _to_int(v):
    if isinstance(v, (list, tuple)):
        return int(v[0])
    return int(v)


@register("retinanet_detection_output")
def retinanet_detection_output(ctx):
    """RetinaNet post-process (parity: retinanet_detection_output_op):
    concatenate levels, sigmoid the class logits, and run the SAME
    per-class NMS core as multiclass_nms with this op's nms_top_k /
    keep_top_k / nms_threshold attrs (RetinaNet has no background
    channel, so every class competes)."""
    bboxes = ctx.in_list("BBoxes")     # per level (N, M, 4)
    scores = ctx.in_list("Scores")     # per level (N, M, C) logits
    allb = jnp.concatenate(bboxes, axis=1)
    alls = jax.nn.sigmoid(jnp.concatenate(scores, axis=1))  # (N, M, C)
    out = _multiclass_nms_arrays(
        allb, jnp.swapaxes(alls, 1, 2),
        ctx.attr("score_threshold", 0.05),
        ctx.attr("nms_threshold", 0.3),
        ctx.attr("nms_top_k", 1000),
        ctx.attr("keep_top_k", 100),
        background=-1)
    return {"Out": out}


@register("detection_map")
def detection_map(ctx):
    """Parity: detection_map_op (VOC mAP). Host-callback kernel (the
    reference computes this C++-side per step; it is a monitoring
    metric, never on the grad path). DetectRes rows [label, score,
    x1,y1,x2,y2]; Label rows [label, x1,y1,x2,y2(,difficult)]. Batch
    rows are evaluated as one image set unless per-image Lengths are
    fed — streaming multi-batch accumulation lives host-side in
    metrics.DetectionMAP."""
    det = ctx.in_("DetectRes")
    gt = ctx.in_("Label")
    thr = ctx.attr("overlap_threshold", 0.3)
    ap_version = ctx.attr("ap_version", "integral")
    background = ctx.attr("background_label", 0)
    eval_difficult = ctx.attr("evaluate_difficult", True)

    def _map(det, gt):
        det = np.asarray(det, np.float32).reshape(-1, 6)
        gt = np.asarray(gt, np.float32)
        gt = gt.reshape(-1, gt.shape[-1])
        if not eval_difficult and gt.shape[-1] >= 6:
            # column 5 is the difficult flag: excluded boxes leave both
            # the recall denominator and the matching pool (VOC drops
            # them from scoring; the don't-penalize-matches nuance is
            # folded into the drop)
            gt = gt[gt[:, 5] < 0.5]
        aps = []
        for cls in np.unique(gt[:, 0]):
            if background is not None and int(cls) == int(background):
                continue
            g = gt[gt[:, 0] == cls][:, 1:5]
            d = det[det[:, 0] == cls]
            if not len(g):
                continue
            order = np.argsort(-d[:, 1])
            matched = np.zeros(len(g), bool)
            tp = np.zeros(len(order)); fp = np.zeros(len(order))
            for r, i in enumerate(order):
                bb = d[i, 2:6]
                ious = _iou_np(bb, g)
                j = int(np.argmax(ious)) if len(ious) else -1
                if j >= 0 and ious[j] >= thr and not matched[j]:
                    tp[r] = 1; matched[j] = True
                else:
                    fp[r] = 1
            ctp, cfp = np.cumsum(tp), np.cumsum(fp)
            rec = ctp / max(len(g), 1)
            prec = ctp / np.maximum(ctp + cfp, 1e-9)
            if ap_version == "11point":
                ap = np.mean([prec[rec >= t].max() if (rec >= t).any()
                              else 0.0 for t in np.linspace(0, 1, 11)])
            else:
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1])
            aps.append(ap)
        return np.float32(np.mean(aps) if aps else 0.0)

    out = jax.pure_callback(
        _map, jax.ShapeDtypeStruct((), jnp.float32), det, gt)
    return {"MAP": out.reshape(1), "Out": out.reshape(1)}


def _iou_np(box, boxes):
    x1 = np.maximum(box[0], boxes[:, 0])
    y1 = np.maximum(box[1], boxes[:, 1])
    x2 = np.minimum(box[2], boxes[:, 2])
    y2 = np.minimum(box[3], boxes[:, 3])
    inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    a1 = (box[2] - box[0]) * (box[3] - box[1])
    a2 = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return inter / np.maximum(a1 + a2 - inter, 1e-9)
