"""Detection ops (subset).

Parity: paddle/fluid/operators/detection/{roi_pool,roi_align,prior_box,
iou_similarity,box_coder,yolo_box}_op.* — enough for the detection demo
models; NMS runs as a host-side utility (paddle_tpu.layers.detection).
"""

import jax
import jax.numpy as jnp

from . import register


@register("iou_similarity")
def iou_similarity(ctx):
    x = ctx.in_("X")  # (N, 4) xmin,ymin,xmax,ymax
    y = ctx.in_("Y")  # (M, 4)
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / jnp.maximum(area_x[:, None] + area_y[None, :] - inter, 1e-10)}


def _roi_grid(x, rois, pooled_h, pooled_w, spatial_scale, sampling=2, align=True):
    """Bilinear ROI align core: x NCHW, rois (R,5) [batch_idx,x1,y1,x2,y2]."""
    n, c, h, w = x.shape
    bidx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:] * spatial_scale
    off = 0.5 if align else 0.0
    x1, y1, x2, y2 = boxes[:, 0] - off, boxes[:, 1] - off, boxes[:, 2] - off, boxes[:, 3] - off
    bw = jnp.maximum(x2 - x1, 1.0) / pooled_w
    bh = jnp.maximum(y2 - y1, 1.0) / pooled_h
    ys = y1[:, None] + bh[:, None] * (jnp.arange(pooled_h * sampling) + 0.5) / sampling
    xs = x1[:, None] + bw[:, None] * (jnp.arange(pooled_w * sampling) + 0.5) / sampling

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = yy - y0
        wx = xx - x0
        va = img[:, y0[:, None], x0[None, :]]
        vb = img[:, y0[:, None], x1_[None, :]]
        vc = img[:, y1_[:, None], x0[None, :]]
        vd = img[:, y1_[:, None], x1_[None, :]]
        return (va * ((1 - wy)[:, None] * (1 - wx)[None, :]) +
                vb * ((1 - wy)[:, None] * wx[None, :]) +
                vc * (wy[:, None] * (1 - wx)[None, :]) +
                vd * (wy[:, None] * wx[None, :]))

    def per_roi(b, yy, xx):
        img = x[b]  # (C, H, W)
        vals = bilinear(img, yy, xx)  # (C, ph*s, pw*s)
        vals = vals.reshape(c, pooled_h, sampling, pooled_w, sampling)
        return vals.mean(axis=(2, 4))

    return jax.vmap(per_roi)(bidx, ys, xs)


@register("roi_align")
def roi_align(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    out = _roi_grid(x, rois, ctx.attr("pooled_height", 1), ctx.attr("pooled_width", 1),
                    ctx.attr("spatial_scale", 1.0), ctx.attr("sampling_ratio", 2) or 2)
    return {"Out": out}


@register("roi_pool")
def roi_pool(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    # Max-pool variant approximated with dense sampling + max
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    out = _roi_grid(x, rois, ph, pw, ctx.attr("spatial_scale", 1.0), sampling=2,
                    align=False)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, jnp.int64)}


@register("psroi_pool")
def psroi_pool(ctx):
    x = ctx.in_("X")
    rois = ctx.in_("ROIs")
    out_c = ctx.attr("output_channels")
    ph = ctx.attr("pooled_height")
    pw = ctx.attr("pooled_width")
    pooled = _roi_grid(x, rois, ph, pw, ctx.attr("spatial_scale", 1.0))
    r = pooled.shape[0]
    pooled = pooled.reshape(r, out_c, ph, pw, ph, pw)
    idx_h = jnp.arange(ph)
    idx_w = jnp.arange(pw)
    out = pooled[:, :, idx_h[:, None], idx_w[None, :], idx_h[:, None], idx_w[None, :]]
    return {"Out": out.reshape(r, out_c, ph, pw)}


@register("box_coder")
def box_coder(ctx):
    prior = ctx.in_("PriorBox")      # (M, 4)
    target = ctx.in_("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
    else:
        d = target  # (N, M, 4) or (M, 4)
        if d.ndim == 2:
            d = d[:, None, :]
        cx = pcx + d[..., 0] * pw
        cy = pcy + d[..., 1] * ph
        w = pw * jnp.exp(d[..., 2])
        h = ph * jnp.exp(d[..., 3])
        out = jnp.stack([cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1)
    return {"OutputBox": out}


@register("yolo_box")
def yolo_box(ctx):
    x = ctx.in_("X")  # (N, A*(5+C), H, W)
    img_size = ctx.in_("ImgSize")
    anchors = ctx.attr("anchors")
    class_num = ctx.attr("class_num")
    conf_thresh = ctx.attr("conf_thresh", 0.01)
    downsample = ctx.attr("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (w * downsample)
    bh = jnp.exp(x[:, :, 3]) * ah / (h * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imgh = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
    imgw = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
    boxes = jnp.stack([(bx - bw / 2) * imgw, (by - bh / 2) * imgh,
                       (bx + bw / 2) * imgw, (by + bh / 2) * imgh], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    probs = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(boxes.dtype)
    return {"Boxes": boxes * mask, "Scores": probs * mask}


@register("prior_box")
def prior_box(ctx):
    inp = ctx.in_("Input")  # (N, C, H, W) feature map
    image = ctx.in_("Image")
    min_sizes = ctx.attr("min_sizes")
    max_sizes = ctx.attr("max_sizes", []) or []
    ars = ctx.attr("aspect_ratios", [1.0])
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    flip = ctx.attr("flip", False)
    clip = ctx.attr("clip", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    full_ars = []
    for ar in ars:
        full_ars.append(ar)
        if flip and ar != 1.0:
            full_ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        for ar in full_ars:
            bw = ms * (ar ** 0.5) / 2.0
            bh = ms / (ar ** 0.5) / 2.0
            boxes.append((bw, bh))
        for Ms in max_sizes:
            s = (ms * Ms) ** 0.5 / 2.0
            boxes.append((s, s))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(cxg - bw) / img_w, (cyg - bh) / img_h,
                              (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1))
    priors = jnp.stack(out, axis=2)  # (H, W, num_priors, 4)
    if clip:
        priors = jnp.clip(priors, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), priors.shape)
    return {"Boxes": priors, "Variances": var}


@register("density_prior_box")
def density_prior_box(ctx):
    """Parity: paddle/fluid/operators/detection/density_prior_box_op.cc —
    dense grids of fixed-size priors per cell (PyramidBox-style)."""
    inp = ctx.in_("Input")
    image = ctx.in_("Image")
    fixed_sizes = ctx.attr("fixed_sizes", [])
    fixed_ratios = ctx.attr("fixed_ratios", [1.0])
    densities = ctx.attr("densities", [1])
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    clip = ctx.attr("clip", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    h, w = inp.shape[2], inp.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = []
    for size, density in zip(fixed_sizes, densities):
        shift_w = sw / density
        shift_h = sh / density
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            for di in range(density):
                for dj in range(density):
                    ccx = cxg - sw / 2.0 + shift_w / 2.0 + dj * shift_w
                    ccy = cyg - sh / 2.0 + shift_h / 2.0 + di * shift_h
                    out.append(jnp.stack(
                        [(ccx - bw / 2.0) / img_w, (ccy - bh / 2.0) / img_h,
                         (ccx + bw / 2.0) / img_w, (ccy + bh / 2.0) / img_h],
                        axis=-1))
    priors = jnp.stack(out, axis=2)  # (H, W, num_priors, 4)
    if clip:
        priors = jnp.clip(priors, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), priors.shape)
    return {"Boxes": priors, "Variances": var}


def _nms_single(boxes, scores, score_thresh, nms_thresh, top_k):
    """Static-shape class-wise NMS core: returns (keep_mask, order) for one
    class. Runs as regular XLA ops (sort + O(K^2) IoU suppress over the
    top_k candidates) — no host round-trip, TPU-friendly."""
    k = min(top_k, scores.shape[0])
    top_scores, order = jax.lax.top_k(scores, k)
    cand = boxes[order]  # (K, 4)
    x1, y1, x2, y2 = cand[:, 0], cand[:, 1], cand[:, 2], cand[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, keep):
        sup = (iou[i] > nms_thresh) & keep[i] & (jnp.arange(k) > i)
        return keep & ~sup

    keep = top_scores > score_thresh
    keep = jax.lax.fori_loop(0, k, body, keep)
    return keep, order, top_scores


@register("multiclass_nms")
def multiclass_nms(ctx):
    """Parity: paddle/fluid/operators/detection/multiclass_nms_op.cc.
    Static-shape output: (N, keep_top_k, 6) [class, score, x1, y1, x2, y2]
    padded with -1 rows (the TPU replacement for the reference's LoD
    variable-length output)."""
    bboxes = ctx.in_("BBoxes")   # (N, M, 4)
    scores = ctx.in_("Scores")   # (N, C, M)
    score_thresh = ctx.attr("score_threshold", 0.01)
    nms_thresh = ctx.attr("nms_threshold", 0.3)
    nms_top_k = ctx.attr("nms_top_k", 64)
    keep_top_k = ctx.attr("keep_top_k", 100)
    background = ctx.attr("background_label", 0)
    n, c, m = scores.shape

    def per_image(boxes_i, scores_i):
        all_scores, all_cls, all_boxes = [], [], []
        for cls in range(c):
            if cls == background:
                continue
            keep, order, top_scores = _nms_single(
                boxes_i, scores_i[cls], score_thresh, nms_thresh, nms_top_k)
            kept_scores = jnp.where(keep, top_scores, -1.0)
            all_scores.append(kept_scores)
            all_cls.append(jnp.full_like(kept_scores, cls))
            all_boxes.append(boxes_i[order])
        cat_scores = jnp.concatenate(all_scores)
        cat_cls = jnp.concatenate(all_cls)
        cat_boxes = jnp.concatenate(all_boxes, axis=0)
        kk = min(keep_top_k, cat_scores.shape[0])
        final_scores, idx = jax.lax.top_k(cat_scores, kk)
        out = jnp.concatenate(
            [cat_cls[idx][:, None], final_scores[:, None], cat_boxes[idx]],
            axis=-1)
        out = jnp.where(final_scores[:, None] > 0, out, -1.0)
        if kk < keep_top_k:
            out = jnp.pad(out, ((0, keep_top_k - kk), (0, 0)),
                          constant_values=-1.0)
        return out

    return {"Out": jax.vmap(per_image)(bboxes, scores)}


@register("ssd_loss")
def ssd_loss(ctx):
    """Parity: fluid.layers.ssd_loss (detection/target_assign + conf/loc
    loss). Simplified static-shape variant: anchors matched to the best gt
    box by IoU; conf = softmax CE, loc = smooth-l1 on matched anchors."""
    loc = ctx.in_("Location")        # (N, M, 4) predicted offsets
    conf = ctx.in_("Confidence")     # (N, M, C) logits
    gt_box = ctx.in_("GtBox")        # (N, G, 4)
    gt_label = ctx.in_("GtLabel")    # (N, G) or (N, G, 1)
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    prior = ctx.in_("PriorBox")      # (M, 4)
    overlap_thresh = ctx.attr("overlap_threshold", 0.5)
    neg_ratio = ctx.attr("neg_pos_ratio", 3.0)

    def iou_mat(a, b):
        ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
        iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
        ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
        iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        aa = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
        ab = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
        return inter / jnp.maximum(aa[:, None] + ab[None, :] - inter, 1e-10)

    def per_image(loc_i, conf_i, gt_b, gt_l):
        iou = iou_mat(prior, gt_b)            # (M, G)
        best_iou = iou.max(axis=1)
        best_gt = iou.argmax(axis=1)
        pos = best_iou > overlap_thresh       # (M,)
        target_label = jnp.where(pos, gt_l[best_gt], 0)
        # localization target: encode matched gt vs prior (center-size)
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = (prior[:, 0] + prior[:, 2]) / 2
        pcy = (prior[:, 1] + prior[:, 3]) / 2
        g = gt_b[best_gt]
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-10)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-10)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        t = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                       jnp.log(gw / pw), jnp.log(gh / ph)], axis=-1)
        diff = jnp.abs(loc_i - t)
        loc_l = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(-1)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        conf_l = -jnp.take_along_axis(logp, target_label[:, None].astype(jnp.int32),
                                      axis=-1)[:, 0]
        # hard negative mining: top (neg_ratio * num_pos) negatives by loss
        num_pos = jnp.maximum(pos.sum(), 1)
        neg_loss = jnp.where(pos, -jnp.inf, conf_l)
        rank = jnp.argsort(jnp.argsort(-neg_loss))
        neg = rank < (neg_ratio * num_pos)
        total = (jnp.where(pos, loc_l + conf_l, 0.0).sum() +
                 jnp.where(neg, conf_l, 0.0).sum())
        return total / num_pos

    return {"Out": jax.vmap(per_image)(loc, conf, gt_box, gt_label)}
