"""Tensor manipulation ops.

Parity: paddle/fluid/operators/{concat,split,reshape,transpose,slice,gather,
scatter,expand,pad,stack,squeeze,unsqueeze,cast,one_hot,top_k,arg_min_max,
fill_constant,assign,...}_op.cc. Static-shape jnp — all attrs are compile-time
constants so XLA can tile/fuse freely.
"""

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from . import register, DEVICE_INT
from ..core.framework import convert_dtype


def _np_dtype(d):
    # int64 policy: requests for 64-bit types narrow to the device
    # widths (values were validated at the feed boundary; see
    # ops/__init__.py DEVICE_INT)
    from . import canon_dtype
    d = canon_dtype(d)
    return {"bool": jnp.bool_}.get(d, jnp.dtype(convert_dtype(d)))


@register("cast")
def cast(ctx):
    return {"Out": ctx.in_("X").astype(_np_dtype(ctx.attr("out_dtype")))}


@register("fill_constant")
def fill_constant(ctx):
    shape = ctx.attr("shape", [1])
    value = ctx.attr("value", 0.0)
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jnp.full(tuple(shape), value, dtype=dtype)}


@register("fill_constant_batch_size_like")
def fill_constant_batch_size_like(ctx):
    ref = ctx.in_("Input")
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": jnp.full(tuple(shape), ctx.attr("value", 0.0),
                            dtype=_np_dtype(ctx.attr("dtype", "float32")))}


@register("assign")
def assign(ctx):
    return {"Out": ctx.in_("X")}


@register("shape")
def shape_op(ctx):
    return {"Out": jnp.asarray(ctx.in_("Input").shape, dtype=jnp.int32)}


@register("rank")
def rank_op(ctx):
    return {"Out": jnp.asarray(ctx.in_("Input").ndim, dtype=jnp.int32)}


@register("size")
def size_op(ctx):
    return {"Out": jnp.asarray(ctx.in_("Input").size, dtype=DEVICE_INT)}


@register("concat")
def concat(ctx):
    return {"Out": jnp.concatenate(ctx.in_list("X"), axis=ctx.attr("axis", 0))}


@register("split")
def split(ctx):
    x = ctx.in_("X")
    axis = ctx.attr("axis", 0)
    sections = ctx.attr("sections")
    num = ctx.attr("num")
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("reshape", "reshape2")
def reshape(ctx):
    x = ctx.in_("X")
    shape = list(ctx.attr("shape"))
    # fluid: 0 means copy input dim; -1 inferred.
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape[:x.ndim])] + \
            [s for s in shape[x.ndim:]]
    return {"Out": x.reshape(tuple(shape)), "XShape": jnp.zeros((0,) + x.shape)}


@register("transpose", "transpose2")
def transpose(ctx):
    return {"Out": jnp.transpose(ctx.in_("X"), ctx.attr("axis")),
            "XShape": jnp.zeros((0,))}


@register("flatten", "flatten2")
def flatten(ctx):
    x = ctx.in_("X")
    axis = ctx.attr("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return {"Out": x.reshape((lead, -1)), "XShape": jnp.zeros((0,))}


@register("squeeze", "squeeze2")
def squeeze(ctx):
    x = ctx.in_("X")
    axes = ctx.attr("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": out, "XShape": jnp.zeros((0,))}


@register("unsqueeze", "unsqueeze2")
def unsqueeze(ctx):
    x = ctx.in_("X")
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    return {"Out": x, "XShape": jnp.zeros((0,))}


@register("stack")
def stack(ctx):
    return {"Y": jnp.stack(ctx.in_list("X"), axis=ctx.attr("axis", 0))}


@register("unstack")
def unstack(ctx):
    x = ctx.in_("X")
    axis = ctx.attr("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis=axis)]}


@register("slice")
def slice_op(ctx):
    x = ctx.in_("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": x[tuple(idx)]}


@register("strided_slice")
def strided_slice(ctx):
    x = ctx.in_("Input")
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(ctx.attr("axes"), ctx.attr("starts"),
                           ctx.attr("ends"), ctx.attr("strides")):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register("gather")
def gather(ctx):
    x, idx = ctx.in_("X"), ctx.in_("Index")
    return {"Out": jnp.take(x, idx.reshape(-1).astype(jnp.int32), axis=0)}


@register("gather_nd")
def gather_nd(ctx):
    x, idx = ctx.in_("X"), ctx.in_("Index")
    idx = idx.astype(jnp.int32)
    # advanced indexing covers both full (k == ndim -> scalars) and
    # partial (k < ndim -> trailing slices) gather_nd semantics
    return {"Out": x[tuple(jnp.moveaxis(idx, -1, 0))]}


@register("scatter")
def scatter(ctx):
    x, idx, upd = ctx.in_("X"), ctx.in_("Ids"), ctx.in_("Updates")
    idx = idx.reshape(-1).astype(jnp.int32)
    if ctx.attr("overwrite", True):
        return {"Out": x.at[idx].set(upd)}
    return {"Out": x.at[idx].add(upd)}


@register("scatter_nd_add")
def scatter_nd_add(ctx):
    x, idx, upd = ctx.in_("X"), ctx.in_("Index"), ctx.in_("Updates")
    idx = idx.astype(jnp.int32)
    return {"Out": x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)}


@register("expand")
def expand(ctx):
    x = ctx.in_("X")
    times = ctx.attr("expand_times")
    return {"Out": jnp.tile(x, tuple(times))}


@register("expand_as")
def expand_as(ctx):
    x, y = ctx.in_("X"), ctx.in_("target_tensor")
    times = [t // s for s, t in zip(x.shape, y.shape)]
    return {"Out": jnp.tile(x, tuple(times))}


@register("tile")
def tile(ctx):
    return {"Out": jnp.tile(ctx.in_("X"), tuple(ctx.attr("repeat_times")))}


@register("pad")
def pad(ctx):
    x = ctx.in_("X")
    p = ctx.attr("paddings")
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pairs, constant_values=ctx.attr("pad_value", 0.0))}


@register("pad2d")
def pad2d(ctx):
    x = ctx.in_("X")  # NCHW
    p = ctx.attr("paddings", [0, 0, 0, 0])  # top,bottom,left,right
    mode = ctx.attr("mode", "constant")
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if ctx.attr("data_format", "NCHW") == "NHWC":
        pairs = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    kw = {"constant_values": ctx.attr("pad_value", 0.0)} if mode == "constant" else {}
    return {"Out": jnp.pad(x, pairs, mode=jmode, **kw)}


@register("pad_constant_like")
def pad_constant_like(ctx):
    x, y = ctx.in_("X"), ctx.in_("Y")
    pairs = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pairs, constant_values=ctx.attr("pad_value", 0.0))}


@register("one_hot", "one_hot_v2")
def one_hot(ctx):
    x = ctx.in_("X")
    depth = ctx.attr("depth")
    x = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    return {"Out": jax.nn.one_hot(x.astype(jnp.int32), depth, dtype=jnp.float32)}


@register("top_k")
def top_k(ctx):
    x = ctx.in_("X")
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(DEVICE_INT)}


@register("arg_max")
def arg_max(ctx):
    return {"Out": jnp.argmax(ctx.in_("X"), axis=ctx.attr("axis", -1)).astype(DEVICE_INT)}


@register("arg_min")
def arg_min(ctx):
    return {"Out": jnp.argmin(ctx.in_("X"), axis=ctx.attr("axis", -1)).astype(DEVICE_INT)}


@register("argsort")
def argsort(ctx):
    x = ctx.in_("X")
    axis = ctx.attr("axis", -1)
    descending = ctx.attr("descending", False)
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(DEVICE_INT)}


@register("where")
def where(ctx):
    # fluid.layers.where(cond) -> indices of true (static upper bound: all)
    cond = ctx.in_("Condition")
    n = cond.size
    idx = jnp.nonzero(cond.reshape(-1), size=n, fill_value=-1)[0]
    return {"Out": idx.reshape(-1, 1).astype(DEVICE_INT)}


@register("where_index_select", "select")
def select(ctx):
    return {"Out": jnp.where(ctx.in_("Condition"), ctx.in_("X"), ctx.in_("Y"))}


@register("reverse")
def reverse(ctx):
    x = ctx.in_("X")
    return {"Out": jnp.flip(x, axis=tuple(a % x.ndim for a in ctx.attr("axis")))}


@register("linspace")
def linspace(ctx):
    return {"Out": jnp.linspace(ctx.attr("start"), ctx.attr("stop"),
                                ctx.attr("num"),
                                dtype=_np_dtype(ctx.attr("dtype", "float32")))}


@register("range")
def range_op(ctx):
    return {"Out": jnp.arange(ctx.attr("start"), ctx.attr("end"),
                              ctx.attr("step"),
                              dtype=_np_dtype(ctx.attr("dtype", "float32")))}


@register("eye")
def eye(ctx):
    return {"Out": jnp.eye(ctx.attr("num_rows"), ctx.attr("num_columns"),
                           dtype=_np_dtype(ctx.attr("dtype", "float32")))}


@register("diag")
def diag(ctx):
    return {"Out": jnp.diag(ctx.in_("Diagonal"))}


@register("zeros_like", "fill_zeros_like", "fill_zeros_like2")
def zeros_like(ctx):
    return {"Out": jnp.zeros_like(ctx.in_("X"))}


@register("ones_like", "fill_any_like")
def ones_like(ctx):
    return {"Out": jnp.full_like(ctx.in_("X"), ctx.attr("value", 1.0))}


@register("lookup_table", "lookup_table_v2", "embedding")
def lookup_table(ctx):
    w = ctx.in_("W")
    ids = ctx.in_("Ids").astype(jnp.int32)
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = ctx.attr("padding_idx", -1)
    out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": out}


@register("label_smooth")
def label_smooth(ctx):
    x = ctx.in_("X")
    eps = ctx.attr("epsilon", 0.1)
    if ctx.has_in("PriorDist"):
        prior = ctx.in_("PriorDist")
        return {"Out": (1 - eps) * x + eps * prior}
    return {"Out": (1 - eps) * x + eps / x.shape[-1]}


@register("unique_with_counts", "unique")
def unique(ctx):
    x = ctx.in_("X")
    n = x.size
    out, idx, counts = jnp.unique(x, return_inverse=True, return_counts=True,
                                  size=n, fill_value=0)
    idt = _np_dtype(ctx.attr("dtype", "int32"))
    return {"Out": out, "Index": idx.astype(idt),
            "Count": counts.astype(idt)}


@register("shard_index")
def shard_index(ctx):
    x = ctx.in_("X")
    index_num = ctx.attr("index_num")
    nshards = ctx.attr("nshards")
    shard_id = ctx.attr("shard_id")
    ignore_value = ctx.attr("ignore_value", -1)
    # shard_index_op.h:37 and the op docstring (shard_index_op.cc:77) —
    # FLOOR division (ceiling is a paddle-2.x change); when nshards does
    # not divide index_num the tail ids land past shard nshards-1 and
    # every shard maps them to ignore_value.
    shard_size = index_num // nshards
    if shard_size == 0:
        raise ValueError(
            f"shard_index: nshards ({nshards}) > index_num ({index_num}) "
            "gives an empty shard_size (the reference divides by zero here)")
    in_shard = (x // shard_size) == shard_id
    return {"Out": jnp.where(in_shard, x % shard_size, ignore_value)}


@register("space_to_depth")
def space_to_depth(ctx):
    x = ctx.in_("X")  # NCHW
    bs = ctx.attr("blocksize")
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": x.reshape(n, c * bs * bs, h // bs, w // bs)}


@register("pixel_shuffle")
def pixel_shuffle(ctx):
    x = ctx.in_("X")  # NCHW
    r = ctx.attr("upscale_factor")
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return {"Out": x.reshape(n, c // (r * r), h * r, w * r)}


@register("shuffle_channel")
def shuffle_channel(ctx):
    x = ctx.in_("X")
    g = ctx.attr("group")
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(n, c, h, w)}


@register("assign_value")
def assign_value(ctx):
    vals = jnp.asarray(ctx.attr("values"), dtype=_np_dtype(ctx.attr("dtype", "float32")))
    return {"Out": vals.reshape(tuple(ctx.attr("shape")))}


@register("scatter_nd")
def scatter_nd(ctx):
    """Zeros of `shape` with updates scattered at index tuples
    (reference: scatter_nd_op)."""
    index, updates = ctx.in_("Index"), ctx.in_("Updates")
    shape = tuple(ctx.attr("shape"))
    out = jnp.zeros(shape, updates.dtype)
    return {"Out": out.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)}


@register("multiplex")
def multiplex(ctx):
    xs = ctx.in_list("X")
    ids = ctx.in_("Ids").reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(xs, axis=0)           # (K, B, ...)
    # (None, slice(:), None...) index tuple spelled out — the starred
    # subscript form needs py3.11+
    idx = (None, slice(None)) + (None,) * (stacked.ndim - 2)
    return {"Out": jnp.take_along_axis(stacked, ids[idx], axis=0)[0]}


@register("crop", "crop_tensor")
def crop_tensor(ctx):
    x = ctx.in_("X")
    shape = ctx.attr("shape")
    offsets = ctx.attr("offsets") or [0] * x.ndim
    if ctx.has_in("Offsets"):
        # offsets may be traced — dynamic_slice starts accept tracers, only
        # the slice SIZES must be static
        if any(s in (-1, 0) for s in shape):
            # a -1/0 shape entry means "rest of the dim from the offset";
            # with a runtime offset that size cannot be static, and
            # dynamic_slice would silently clamp the start back to 0.
            raise NotImplementedError(
                "crop/crop_tensor: -1/0 entries in `shape` cannot be "
                "combined with a tensor Offsets input (the slice size "
                "would be dynamic); pass explicit sizes")
        off = ctx.in_("Offsets").reshape(-1).astype(jnp.int32)
        offsets = [off[i] for i in range(x.ndim)]
        static_off = [0] * x.ndim
    else:
        static_off = offsets
    shape = [x.shape[i] - static_off[i] if s in (-1, 0) else s
             for i, s in enumerate(shape)]
    return {"Out": lax.dynamic_slice(x, offsets, shape)}


@register("hash")
def hash_op(ctx):
    """Mod-space hashing of int ids (reference: hash_op, sparse CTR
    feature crossing). DOCUMENTED DIVERGENCE: the reference hashes the
    raw int64 bytes with XXH64 per seed; this kernel uses 32-bit
    multiplicative hashing (golden-ratio odd constant) — same
    determinism, same [0, mod_by) bucket contract, different bucket
    VALUES, so fluid-trained embeddings keyed by reference hash buckets
    cannot be ported bit-for-bit through this op (retrain or remap
    buckets). Id width is bounded by the int64 policy (MIGRATION.md
    'Integer dtypes': device ints are int32, the feed boundary errors
    past 2^31), so no two REACHABLE ids collide by truncation."""
    x = ctx.in_("X")
    num_hash = ctx.attr("num_hash", 1)
    mod_by = ctx.attr("mod_by", 100000007)
    # the golden-ratio constant must be a uint32 ARRAY scalar: as a bare
    # python literal it exceeds int32 and jax's weak typing overflows
    seeds = (jnp.arange(1, num_hash + 1, dtype=jnp.uint32)
             * jnp.uint32(0x9E3779B1))
    h = (x[..., None].astype(jnp.uint32) * seeds) % jnp.uint32(mod_by)
    return {"Out": h.astype(jnp.int32).reshape(x.shape[:-1] + (num_hash * x.shape[-1],))}


@register("lod_reset", "lod_append")
def lod_reset(ctx):
    """Parity: lod_reset_op / lod_append. LoD is host-side metadata in
    this framework (SURVEY.md design decision 4: device tensors are
    pad+mask, `core/lod.py` carries offsets) — on-device this op is the
    identity; the new LoD rides the layer-level attr."""
    return {"Out": ctx.in_("X")}


@register("merge_selected_rows")
def merge_selected_rows(ctx):
    """Parity: merge_selected_rows_op. SelectedRows is re-designed away:
    sparse grads flow dense (XLA scatter-add at the embedding), so merge
    is the identity."""
    return {"Out": ctx.in_("X")}


@register("get_tensor_from_selected_rows")
def get_tensor_from_selected_rows(ctx):
    return {"Out": ctx.in_("X")}
