"""Collective communication ops.

Parity: paddle/fluid/operators/collective/{c_allreduce,c_broadcast,
c_allgather,c_reducescatter,c_sync_*}_op.* — the NCCL ring collectives.

TPU-first redesign: these lower to XLA collectives (psum/all_gather/
ppermute/psum_scatter) which ride the ICI mesh. They are meaningful inside a
shard_map/pmap context where the named axis exists; when traced outside any
mapped context (single-chip program) they degrade to identity, mirroring how
a 1-GPU NCCL ring is a no-op.
"""

import jax
from jax import lax

from . import register


def _axis(ctx):
    return ctx.attr("ring_id_axis", ctx.attr("axis_name", "dp"))


def _in_mapped_context(axis):
    try:
        jax.core.get_axis_env().axis_size(axis) if hasattr(jax.core, "get_axis_env") else lax.axis_index(axis)
        return True
    except (NameError, Exception):
        return False


def _maybe(fn, x, axis):
    try:
        return fn(x, axis)
    except NameError:
        return x  # axis not bound: single-device trace


@register("c_allreduce_sum", "c_allreduce", "allreduce")
def c_allreduce_sum(ctx):
    x = ctx.in_("X")
    return {"Out": _maybe(lax.psum, x, _axis(ctx))}


@register("c_allreduce_max")
def c_allreduce_max(ctx):
    return {"Out": _maybe(lax.pmax, ctx.in_("X"), _axis(ctx))}


@register("c_allreduce_min")
def c_allreduce_min(ctx):
    return {"Out": _maybe(lax.pmin, ctx.in_("X"), _axis(ctx))}


@register("c_allreduce_prod")
def c_allreduce_prod(ctx):
    x = ctx.in_("X")

    def pprod(v, ax):
        # no lax.pprod primitive: gather the ring then reduce. An
        # exp(psum(log)) trick would NaN on negatives and -inf on zeros.
        import jax.numpy as jnp
        return jnp.prod(lax.all_gather(v, ax, axis=0), axis=0)
    return {"Out": _maybe(pprod, x, _axis(ctx))}


@register("c_broadcast", "broadcast")
def c_broadcast(ctx):
    x = ctx.in_("X")
    axis = _axis(ctx)
    root = ctx.attr("root", 0)

    def bcast(v, ax):
        idx = lax.axis_index(ax)
        import jax.numpy as jnp
        src = lax.psum(jnp.where(idx == root, v, jnp.zeros_like(v)), ax)
        return src
    return {"Out": _maybe(bcast, x, axis)}


@register("c_allgather")
def c_allgather(ctx):
    x = ctx.in_("X")

    def gather(v, ax):
        return lax.all_gather(v, ax, axis=0, tiled=True)
    return {"Out": _maybe(gather, x, _axis(ctx))}


@register("c_reducescatter")
def c_reducescatter(ctx):
    x = ctx.in_("X")

    def rs(v, ax):
        return lax.psum_scatter(v, ax, scatter_dimension=0, tiled=True)
    return {"Out": _maybe(rs, x, _axis(ctx))}


@register("alltoall")
def alltoall(ctx):
    x = ctx.in_("X")

    def a2a(v, ax):
        return lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=True)
    return {"Out": _maybe(a2a, x, _axis(ctx))}


@register("c_sync_calc_stream", "c_sync_comm_stream")
def c_sync(ctx):
    # XLA schedules compute/comm overlap itself; sync is a no-op by design.
    return {"Out": ctx.in_("X")}


@register("moe")
def moe(ctx):
    """Framework-level Mixture-of-Experts FFN (expert parallelism over
    the mesh 'ep' axis via all_to_all dispatch; dense all-experts-local
    fallback off-mesh). The TPU re-expression of the reference's
    conditional-compute scale story — see parallel/moe.py moe_apply."""
    from ..parallel.moe import moe_apply

    out, aux = moe_apply(
        ctx.in_("X"), ctx.in_("GateW"), ctx.in_("WUp"), ctx.in_("WDown"),
        capacity_factor=ctx.attr("capacity_factor", 1.25))
    return {"Out": out, "AuxLoss": aux.reshape(1)}
