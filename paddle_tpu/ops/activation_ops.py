"""Activation ops.

Parity: paddle/fluid/operators/activation_op.cc (the full fluid.layers.ops
activation list). Pure elementwise jnp/lax — XLA fuses these into the
preceding matmul/conv epilogue on TPU.
"""

import jax
import jax.numpy as jnp
from jax import lax

from . import register


def _unary(fn):
    def impl(ctx):
        return {"Out": fn(ctx.in_("X"))}
    return impl


register("relu")(_unary(jax.nn.relu))
register("sigmoid")(_unary(jax.nn.sigmoid))
register("logsigmoid")(_unary(jax.nn.log_sigmoid))
register("tanh")(_unary(jnp.tanh))
register("tanh_shrink")(_unary(lambda x: x - jnp.tanh(x)))
register("exp")(_unary(jnp.exp))
register("log")(_unary(jnp.log))
register("sqrt")(_unary(jnp.sqrt))
register("rsqrt")(_unary(lax.rsqrt))
register("abs")(_unary(jnp.abs))
register("ceil")(_unary(jnp.ceil))
register("floor")(_unary(jnp.floor))
register("round")(_unary(jnp.round))
register("cos")(_unary(jnp.cos))
register("sin")(_unary(jnp.sin))
register("acos")(_unary(jnp.arccos))
register("asin")(_unary(jnp.arcsin))
register("atan")(_unary(jnp.arctan))
register("reciprocal")(_unary(jnp.reciprocal))
register("square")(_unary(jnp.square))
register("softplus")(_unary(jax.nn.softplus))
register("softsign")(_unary(jax.nn.soft_sign))
register("sign")(_unary(jnp.sign))
# exact erf form (the reference's gelu op); the tanh approximation is what
# jax defaults to, but fluid tests compare against erf
register("gelu")(_unary(lambda x: jax.nn.gelu(x, approximate=False)))
register("erf")(_unary(lax.erf))


@register("pow")
def pow_op(ctx):
    factor = ctx.in_("FactorTensor", ctx.attr("factor", 1.0))
    return {"Out": jnp.power(ctx.in_("X"), factor)}


@register("leaky_relu")
def leaky_relu(ctx):
    alpha = ctx.attr("alpha", 0.02)
    x = ctx.in_("X")
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register("elu")
def elu(ctx):
    return {"Out": jax.nn.elu(ctx.in_("X"), ctx.attr("alpha", 1.0))}


@register("selu")
def selu(ctx):
    return {"Out": jax.nn.selu(ctx.in_("X"))}


@register("relu6")
def relu6(ctx):
    t = ctx.attr("threshold", 6.0)
    return {"Out": jnp.clip(ctx.in_("X"), 0.0, t)}


@register("brelu")
def brelu(ctx):
    return {"Out": jnp.clip(ctx.in_("X"), ctx.attr("t_min", 0.0), ctx.attr("t_max", 24.0))}


@register("soft_relu")
def soft_relu(ctx):
    t = ctx.attr("threshold", 40.0)
    x = jnp.clip(ctx.in_("X"), -t, t)
    return {"Out": jnp.log1p(jnp.exp(x))}


@register("swish")
def swish(ctx):
    beta = ctx.attr("beta", 1.0)
    x = ctx.in_("X")
    return {"Out": x * jax.nn.sigmoid(beta * x)}


@register("hard_swish")
def hard_swish(ctx):
    x = ctx.in_("X")
    t = ctx.attr("threshold", 6.0)
    s = ctx.attr("scale", 6.0)
    o = ctx.attr("offset", 3.0)
    return {"Out": x * jnp.clip(x + o, 0.0, t) / s}


@register("hard_sigmoid")
def hard_sigmoid(ctx):
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    return {"Out": jnp.clip(slope * ctx.in_("X") + offset, 0.0, 1.0)}


@register("hard_shrink")
def hard_shrink(ctx):
    t = ctx.attr("threshold", 0.5)
    x = ctx.in_("X")
    return {"Out": jnp.where(jnp.abs(x) > t, x, 0.0)}


@register("softshrink")
def softshrink(ctx):
    lam = ctx.attr("lambda", 0.5)
    x = ctx.in_("X")
    return {"Out": jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))}


@register("thresholded_relu")
def thresholded_relu(ctx):
    t = ctx.attr("threshold", 1.0)
    x = ctx.in_("X")
    return {"Out": jnp.where(x > t, x, 0.0)}


@register("stanh")
def stanh(ctx):
    a = ctx.attr("scale_a", 0.67)
    b = ctx.attr("scale_b", 1.7159)
    return {"Out": b * jnp.tanh(a * ctx.in_("X"))}


@register("prelu")
def prelu(ctx):
    x = ctx.in_("X")
    alpha = ctx.in_("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "channel" and alpha.size > 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register("maxout")
def maxout(ctx):
    x = ctx.in_("X")  # NCHW
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, c // groups, groups, h, w).max(axis=2)}


@register("softmax")
def softmax(ctx):
    return {"Out": jax.nn.softmax(ctx.in_("X"), axis=ctx.attr("axis", -1))}


@register("log_softmax")
def log_softmax(ctx):
    return {"Out": jax.nn.log_softmax(ctx.in_("X"), axis=ctx.attr("axis", -1))}


register("mish")(_unary(lambda x: x * jnp.tanh(jax.nn.softplus(x))))
