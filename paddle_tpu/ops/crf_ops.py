"""Linear-chain CRF: log-likelihood + Viterbi decoding.

Parity: paddle/fluid/operators/linear_chain_crf_op.* and crf_decoding_op.*
(layer API: python/paddle/fluid/layers/nn.py linear_chain_crf:1409,
crf_decoding). The reference walks LoD sequences sequentially on the CPU;
TPU-native both passes are batched `lax.scan`s over the padded time axis
with per-sequence length masks (SURVEY.md design decision 4), so the
whole batch advances one timestep per scan step on the VPU.

Transition parameter layout matches the reference exactly:
    transition[0]  = start scores   (alpha_0 contribution)
    transition[1]  = end scores     (added at each sequence's last step)
    transition[2:] = (N, N) matrix, [i, j] = score of tag i -> tag j.
"""

import jax
import jax.numpy as jnp

from . import register, DEVICE_INT


def _split_transition(w):
    return w[0], w[1], w[2:]          # start (N,), end (N,), trans (N, N)


def _crf_inputs(ctx):
    em = ctx.in_("Emission").astype(jnp.float32)     # (B, T, N)
    w = ctx.in_("Transition").astype(jnp.float32)    # (N + 2, N)
    length = ctx.in_("Length")
    if length is None:
        length = jnp.full((em.shape[0],), em.shape[1], jnp.int32)
    return em, w, length.reshape(-1).astype(jnp.int32)


@register("linear_chain_crf")
def linear_chain_crf(ctx):
    """Returns the negative log-likelihood per sequence (the cost the
    reference's crf layer feeds to the optimizer), plus the alpha table
    for parity with the reference's output set."""
    em, w, length = _crf_inputs(ctx)
    label = ctx.in_("Label")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)                   # (B, T)
    b, t, n = em.shape
    start, end, trans = _split_transition(w)

    # ---- gold path score -------------------------------------------------
    t_idx = jnp.arange(t)
    valid = t_idx[None, :] < length[:, None]          # (B, T)
    em_score = jnp.where(
        valid, jnp.take_along_axis(em, label[..., None], -1)[..., 0], 0.0
    ).sum(-1)
    pair_valid = valid[:, 1:]                          # step t-1 -> t exists
    pair = trans[label[:, :-1], label[:, 1:]]          # (B, T-1)
    trans_score = jnp.where(pair_valid, pair, 0.0).sum(-1)
    last = jnp.clip(length - 1, 0, t - 1)
    last_tag = jnp.take_along_axis(label, last[:, None], 1)[:, 0]
    score = em_score + trans_score + start[label[:, 0]] + end[last_tag]

    # ---- partition function (forward algorithm) ---------------------------
    def step(alpha, xs):
        e_t, active = xs                               # (B, N), (B,)
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + e_t
        alpha = jnp.where(active[:, None], nxt, alpha)
        return alpha, alpha

    alpha0 = start[None] + em[:, 0]                    # (B, N)
    active = (t_idx[None, 1:] < length[:, None]).T     # (T-1, B)
    alpha_last, alphas = jax.lax.scan(
        step, alpha0, (jnp.moveaxis(em[:, 1:], 1, 0), active))
    log_z = jax.nn.logsumexp(alpha_last + end[None], axis=-1)

    nll = (log_z - score)[:, None]                     # (B, 1)
    alpha_full = jnp.concatenate([alpha0[:, None], jnp.moveaxis(alphas, 0, 1)],
                                 axis=1)
    return {"LogLikelihood": nll,
            "Alpha": alpha_full,
            "EmissionExps": jnp.exp(em),
            "TransitionExps": jnp.exp(w)}


@register("crf_decoding")
def crf_decoding(ctx):
    """Viterbi path (B, T) int64; positions past each length are 0.
    With a Label input, returns per-position mismatch mask instead
    (the reference's evaluation mode)."""
    em, w, length = _crf_inputs(ctx)
    b, t, n = em.shape
    start, end, trans = _split_transition(w)
    t_idx = jnp.arange(t)

    def fwd(carry, xs):
        delta = carry                                   # (B, N)
        e_t, active = xs
        cand = delta[:, :, None] + trans[None]          # (B, N, N)
        best_prev = jnp.argmax(cand, axis=1)            # (B, N)
        nxt = jnp.max(cand, axis=1) + e_t
        delta = jnp.where(active[:, None], nxt, delta)
        return delta, best_prev

    delta0 = start[None] + em[:, 0]
    active = (t_idx[None, 1:] < length[:, None]).T
    delta_last, back = jax.lax.scan(
        fwd, delta0, (jnp.moveaxis(em[:, 1:], 1, 0), active))
    # end scores apply at each sequence's own last position: since padded
    # steps freeze delta, delta_last IS each sequence's final delta.
    last_tag = jnp.argmax(delta_last + end[None], axis=-1)  # (B,)

    def bwd(tag, xs):
        bp, step_t = xs                                  # (B, N), scalar
        prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
        # only backtrack through steps that were active for this sequence
        tag_out = jnp.where(step_t < length, prev, tag)
        return tag_out, tag_out

    # walk t-1 ... 1; emit the tag at each earlier position
    steps = jnp.arange(1, t)
    _, rev_tags = jax.lax.scan(bwd, last_tag, (back, steps), reverse=True)
    path = jnp.concatenate([jnp.moveaxis(rev_tags, 0, 1),
                            last_tag[:, None]], axis=1)  # (B, T)
    # positions beyond length emit 0 (the reference's LoD output simply
    # ends; padded form zero-fills)
    path = jnp.where(t_idx[None] < length[:, None], path, 0)
    path = path.astype(DEVICE_INT)

    label = ctx.in_("Label")
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        err = (path != label.astype(path.dtype)) & \
            (t_idx[None] < length[:, None])
        return {"ViterbiPath": err.astype(DEVICE_INT)}
    return {"ViterbiPath": path}
