"""Text-matching / tree ops: match_matrix_tensor, var_conv_2d,
sequence_scatter, sequence_topk_avg_pooling, tree_conv.

Parity: paddle/fluid/operators/match_matrix_tensor_op.*, var_conv_2d_op.*,
sequence_scatter_op.*, sequence_topk_avg_pooling_op.*, tree_conv_op.*
(layer API python/paddle/fluid/layers/nn.py). These are the MatchPyramid /
TBCNN family the reference runs over LoD tensors with per-sequence CPU
loops; TPU-native every op is batched static-shape with length masks
(SURVEY.md design decision 4) and the bilinear/conv cores ride the MXU.
"""

import jax
import jax.numpy as jnp

from . import register


@register("match_matrix_tensor")
def match_matrix_tensor(ctx):
    """X (B, Lx, D), Y (B, Ly, D), W (D, C, D):
    Out[b, c, i, j] = x_bi . W_c . y_bj — one einsum, C MXU matmuls."""
    x = ctx.in_("X").astype(jnp.float32)
    y = ctx.in_("Y").astype(jnp.float32)
    w = ctx.in_("W").astype(jnp.float32)
    out = jnp.einsum("bid,dce,bje->bcij", x, w, y)
    x_len = ctx.in_("XLength")
    y_len = ctx.in_("YLength")
    if x_len is not None:
        mask = jnp.arange(x.shape[1])[None] < x_len.reshape(-1, 1)
        out = out * mask[:, None, :, None]
    if y_len is not None:
        mask = jnp.arange(y.shape[1])[None] < y_len.reshape(-1, 1)
        out = out * mask[:, None, None, :]
    return {"Out": out, "Tmp": jnp.einsum("bid,dce->bice", x, w)}


@register("var_conv_2d")
def var_conv_2d(ctx):
    """Per-row variable-size conv: a regular XLA conv over the padded
    (B, C, H, W) batch with outputs zeroed beyond each row's valid
    region — what the reference's per-sample CPU im2col computes, at
    fixed shapes."""
    x = ctx.in_("X")                                 # (B, C, H, W)
    w = ctx.in_("W")                                 # (Cout, Cin, kh, kw)
    stride = ctx.attr("strides", [1, 1])
    row = ctx.in_("Row")                             # (B,) valid heights
    col = ctx.in_("Col")                             # (B,) valid widths
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    b, c, h, wd = out.shape
    if row is not None:
        oh = (row.reshape(-1) + stride[0] - 1) // stride[0]
        out = out * (jnp.arange(h)[None] < oh[:, None])[:, None, :, None]
    if col is not None:
        ow = (col.reshape(-1) + stride[1] - 1) // stride[1]
        out = out * (jnp.arange(wd)[None] < ow[:, None])[:, None, None, :]
    return {"Out": out}


@register("sequence_scatter")
def sequence_scatter(ctx):
    """X (B, D); per row b, X[b, ids[b, k]] += updates[b, k] for the
    row's valid k (length mask) — the padded form of the reference's
    LoD-walk scatter."""
    x = ctx.in_("X")
    ids = ctx.in_("Ids").astype(jnp.int32)           # (B, L)
    upd = ctx.in_("Updates")                         # (B, L)
    if ids.ndim == 3:
        ids = ids[..., 0]
    lengths = ctx.in_("Length")
    if lengths is not None:
        valid = jnp.arange(ids.shape[1])[None] < lengths.reshape(-1, 1)
        upd = jnp.where(valid, upd, 0.0)
    b = x.shape[0]
    rows = jnp.repeat(jnp.arange(b)[:, None], ids.shape[1], 1)
    return {"Out": x.at[rows, ids].add(upd)}


@register("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(ctx):
    """X (B, C, L1, L2): for each (b, c, i) the mean of the top-k valid
    entries over j, for every k in `topks`. Out (B, L1, C * len(topks)),
    zero past the row length — the padded form of the reference's
    per-sequence output."""
    x = ctx.in_("X").astype(jnp.float32)
    topks = list(ctx.attr("topks"))
    b, c, l1, l2 = x.shape
    row = ctx.in_("Row")                             # (B,) valid i
    col = ctx.in_("Col")                             # (B,) valid j
    if col is not None:
        jmask = jnp.arange(l2)[None] < col.reshape(-1, 1)   # (B, L2)
        x = jnp.where(jmask[:, None, None, :], x, -jnp.inf)
    kmax = min(max(topks), l2)
    top = jax.lax.top_k(x, kmax)[0]                  # (B, C, L1, kmax)
    top = jnp.where(jnp.isfinite(top), top, 0.0)
    csum = jnp.cumsum(top, axis=-1)                  # prefix sums
    outs = []
    for k in topks:
        kk = min(k, kmax)
        # average over min(k, valid_count) entries (reference divides by k)
        outs.append(csum[..., kk - 1] / float(k))    # (B, C, L1)
    out = jnp.stack(outs, axis=-1)                   # (B, C, L1, K)
    out = out.transpose(0, 2, 1, 3).reshape(b, l1, c * len(topks))
    if row is not None:
        imask = jnp.arange(l1)[None] < row.reshape(-1, 1)
        out = out * imask[:, :, None]
    return {"Out": out}


@register("tree_conv")
def tree_conv(ctx):
    """TBCNN tree convolution (continuous binary tree, Mou et al.),
    exact reference semantics for ANY max_depth (tree_conv_op.h:27,
    math/tree2col.cc:23,85; eta formulas tree2col.h:34-52):

    - NodesVector (B, N, D); node ids are 1-based, row id-1 holds the
      feature. EdgeSet (B, E, 2) (parent, child) pairs, 0-padded;
      construct_tree stops at the FIRST u==0||v==0 pair
      (tree2col.cc:72-77 breaks, not skips), so interior zeros
      terminate the edge list here too.
    - Filter (D, 3, H, F); slot order along axis 1 is
      [W_left, W_right, W_top] — tree2col.cc:121-126 writes the patch
      as (eta_l, eta_r, eta_t) per feature and the op flattens Filter
      to (3D, H*F) against it.
    - For each root u the window is every descendant v at tree depth
      k < max_depth, weighted eta_t=(d-k)/d, eta_l=(1-eta_t)*temp,
      eta_r=(1-eta_t)*(1-eta_l) with temp = (index-1)/(pclen-1) by
      sibling position (0.5 for an only child). Rows past
      node_count = #valid_edges + 1 stay zero (MatMul writes only
      patch_count rows, tree_conv_op.h:72).

    TPU form: depth-k reachability via boolean adjacency powers — one
    (N,N)x(N,D) matmul pair per depth level, no per-node DFS, fully
    static shapes; the reference's stack walk has no XLA analog."""
    nodes = ctx.in_("NodesVector").astype(jnp.float32)   # (B, N, D)
    edges = ctx.in_("EdgeSet").astype(jnp.int32)         # (B, E, 2)
    filt = ctx.in_("Filter").astype(jnp.float32)         # (D, 3, H, F)
    max_depth = int(ctx.attr("max_depth", 2))
    b, n, d_feat = nodes.shape
    w_left, w_right, w_top = filt[:, 0], filt[:, 1], filt[:, 2]  # (D, H, F)
    dd = float(max_depth)

    def per_sample(nv, ed):
        parent, child = ed[:, 0], ed[:, 1]               # (E,) 1-based
        # prefix-valid: the reference breaks at the first padded pair
        valid = jnp.cumprod(
            ((parent > 0) & (child > 0)).astype(jnp.int32)) == 1
        vf = valid.astype(jnp.float32)
        p = jnp.where(valid, parent - 1, 0)              # 0-based rows
        ch = jnp.where(valid, child - 1, 0)
        # adjacency parent->child; a tree, so entries are 0/1
        adj = jnp.zeros((n, n), jnp.float32).at[p, ch].add(vf)
        adj = jnp.minimum(adj, 1.0)
        # per-node sibling stats (independent of the patch root):
        # index = 1-based position among the parent's children in edge
        # order; pclen = that parent's child count (tree2col.cc:40-44)
        earlier = (p[:, None] == p[None, :]) & (
            jnp.arange(len(p))[None, :] < jnp.arange(len(p))[:, None])
        idx0 = (earlier * vf[None, :]).sum(-1)           # 0-based index
        cnt = jax.ops.segment_sum(vf, p, num_segments=n)[p]
        temp_e = jnp.where(cnt > 1, idx0 / jnp.maximum(cnt - 1.0, 1.0), 0.5)
        # scatter per-edge temp onto the child node (unique parent)
        temp = jnp.zeros((n,)).at[ch].add(temp_e * vf)   # (N,)

        node_count = vf.sum() + 1.0
        row_ok = (jnp.arange(n) < node_count).astype(jnp.float32)

        # With s = k/d: eta_t = 1-s, eta_l = s*temp,
        # eta_r = s*(1 - s*temp) — every level's weighted feature sum is
        # a linear combo of reach@nv and reach@(nv*temp), so accumulate
        # those per level and project through the three filters ONCE.
        nvl = nv * temp[:, None]
        reach = jnp.eye(n)                               # depth-0
        ft = jnp.zeros_like(nv)
        fl = jnp.zeros_like(nv)
        fr = jnp.zeros_like(nv)
        for k in range(max_depth):
            s = k / dd
            rn, rnl = reach @ nv, reach @ nvl            # (N, D)
            ft = ft + (1.0 - s) * rn
            fl = fl + s * rnl
            fr = fr + s * rn - s * s * rnl
            if k + 1 < max_depth:
                reach = jnp.minimum(reach @ adj, 1.0)
        out = (jnp.einsum("nd,dhf->nhf", ft, w_top)
               + jnp.einsum("nd,dhf->nhf", fl, w_left)
               + jnp.einsum("nd,dhf->nhf", fr, w_right))
        return out * row_ok[:, None, None]

    out = jax.vmap(per_sample)(nodes, edges)
    bias = ctx.in_("Bias")
    if bias is not None:
        out = out + bias.reshape(1, 1, *bias.shape[-2:]) \
            if bias.ndim >= 2 else out + bias.reshape(1, 1, -1, 1)
    return {"Out": out}
