"""Text-matching / tree ops: match_matrix_tensor, var_conv_2d,
sequence_scatter, sequence_topk_avg_pooling, tree_conv.

Parity: paddle/fluid/operators/match_matrix_tensor_op.*, var_conv_2d_op.*,
sequence_scatter_op.*, sequence_topk_avg_pooling_op.*, tree_conv_op.*
(layer API python/paddle/fluid/layers/nn.py). These are the MatchPyramid /
TBCNN family the reference runs over LoD tensors with per-sequence CPU
loops; TPU-native every op is batched static-shape with length masks
(SURVEY.md design decision 4) and the bilinear/conv cores ride the MXU.
"""

import jax
import jax.numpy as jnp

from . import register


@register("match_matrix_tensor")
def match_matrix_tensor(ctx):
    """X (B, Lx, D), Y (B, Ly, D), W (D, C, D):
    Out[b, c, i, j] = x_bi . W_c . y_bj — one einsum, C MXU matmuls."""
    x = ctx.in_("X").astype(jnp.float32)
    y = ctx.in_("Y").astype(jnp.float32)
    w = ctx.in_("W").astype(jnp.float32)
    out = jnp.einsum("bid,dce,bje->bcij", x, w, y)
    x_len = ctx.in_("XLength")
    y_len = ctx.in_("YLength")
    if x_len is not None:
        mask = jnp.arange(x.shape[1])[None] < x_len.reshape(-1, 1)
        out = out * mask[:, None, :, None]
    if y_len is not None:
        mask = jnp.arange(y.shape[1])[None] < y_len.reshape(-1, 1)
        out = out * mask[:, None, None, :]
    return {"Out": out, "Tmp": jnp.einsum("bid,dce->bice", x, w)}


@register("var_conv_2d")
def var_conv_2d(ctx):
    """Per-row variable-size conv: a regular XLA conv over the padded
    (B, C, H, W) batch with outputs zeroed beyond each row's valid
    region — what the reference's per-sample CPU im2col computes, at
    fixed shapes."""
    x = ctx.in_("X")                                 # (B, C, H, W)
    w = ctx.in_("W")                                 # (Cout, Cin, kh, kw)
    stride = ctx.attr("strides", [1, 1])
    row = ctx.in_("Row")                             # (B,) valid heights
    col = ctx.in_("Col")                             # (B,) valid widths
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    b, c, h, wd = out.shape
    if row is not None:
        oh = (row.reshape(-1) + stride[0] - 1) // stride[0]
        out = out * (jnp.arange(h)[None] < oh[:, None])[:, None, :, None]
    if col is not None:
        ow = (col.reshape(-1) + stride[1] - 1) // stride[1]
        out = out * (jnp.arange(wd)[None] < ow[:, None])[:, None, None, :]
    return {"Out": out}


@register("sequence_scatter")
def sequence_scatter(ctx):
    """X (B, D); per row b, X[b, ids[b, k]] += updates[b, k] for the
    row's valid k (length mask) — the padded form of the reference's
    LoD-walk scatter."""
    x = ctx.in_("X")
    ids = ctx.in_("Ids").astype(jnp.int32)           # (B, L)
    upd = ctx.in_("Updates")                         # (B, L)
    if ids.ndim == 3:
        ids = ids[..., 0]
    lengths = ctx.in_("Length")
    if lengths is not None:
        valid = jnp.arange(ids.shape[1])[None] < lengths.reshape(-1, 1)
        upd = jnp.where(valid, upd, 0.0)
    b = x.shape[0]
    rows = jnp.repeat(jnp.arange(b)[:, None], ids.shape[1], 1)
    return {"Out": x.at[rows, ids].add(upd)}


@register("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(ctx):
    """X (B, C, L1, L2): for each (b, c, i) the mean of the top-k valid
    entries over j, for every k in `topks`. Out (B, L1, C * len(topks)),
    zero past the row length — the padded form of the reference's
    per-sequence output."""
    x = ctx.in_("X").astype(jnp.float32)
    topks = list(ctx.attr("topks"))
    b, c, l1, l2 = x.shape
    row = ctx.in_("Row")                             # (B,) valid i
    col = ctx.in_("Col")                             # (B,) valid j
    if col is not None:
        jmask = jnp.arange(l2)[None] < col.reshape(-1, 1)   # (B, L2)
        x = jnp.where(jmask[:, None, None, :], x, -jnp.inf)
    kmax = min(max(topks), l2)
    top = jax.lax.top_k(x, kmax)[0]                  # (B, C, L1, kmax)
    top = jnp.where(jnp.isfinite(top), top, 0.0)
    csum = jnp.cumsum(top, axis=-1)                  # prefix sums
    outs = []
    for k in topks:
        kk = min(k, kmax)
        # average over min(k, valid_count) entries (reference divides by k)
        outs.append(csum[..., kk - 1] / float(k))    # (B, C, L1)
    out = jnp.stack(outs, axis=-1)                   # (B, C, L1, K)
    out = out.transpose(0, 2, 1, 3).reshape(b, l1, c * len(topks))
    if row is not None:
        imask = jnp.arange(l1)[None] < row.reshape(-1, 1)
        out = out * imask[:, :, None]
    return {"Out": out}


@register("tree_conv")
def tree_conv(ctx):
    """TBCNN tree convolution (continuous binary tree, Mou et al. — the
    design the reference's tree_conv_op implements). NodesVector
    (B, N, D); EdgeSet (B, E, 2) (parent, child) pairs, -1 padded;
    Filter (D, 3, H, F). The window is each node + its direct children;
    weights mix W_top for the parent and a left/right-interpolated pair
    for children by position. Out (B, N, H, F)."""
    if ctx.attr("max_depth", 2) != 2:
        raise NotImplementedError(
            "tree_conv: only max_depth=2 (node + direct children) is "
            "implemented; deeper windows need multi-hop aggregation")
    nodes = ctx.in_("NodesVector").astype(jnp.float32)   # (B, N, D)
    edges = ctx.in_("EdgeSet").astype(jnp.int32)         # (B, E, 2)
    filt = ctx.in_("Filter").astype(jnp.float32)         # (D, 3, H, F)
    b, n, d = nodes.shape
    w_top, w_left, w_right = filt[:, 0], filt[:, 1], filt[:, 2]  # (D, H, F)

    def per_sample(nv, ed):
        parent, child = ed[:, 0], ed[:, 1]               # (E,)
        valid = (parent >= 0) & (child >= 0)
        p = jnp.where(valid, parent, 0)
        ch = jnp.where(valid, child, 0)
        vf = valid.astype(jnp.float32)
        # child position among its siblings: rank by edge order
        ones = jnp.where(valid, 1.0, 0.0)
        # cumulative count of previous children of the same parent
        same = (p[:, None] == p[None, :]) & (jnp.arange(len(p))[None, :]
                                             < jnp.arange(len(p))[:, None])
        pos = (same * ones[None, :]).sum(-1)             # (E,)
        cnt = jax.ops.segment_sum(ones, p, num_segments=n)[p]  # siblings
        denom = jnp.maximum(cnt - 1.0, 1.0)
        eta_r = jnp.where(cnt > 1, pos / denom, 0.5)
        eta_l = 1.0 - eta_r
        cx = nv[ch]                                       # (E, D)
        contrib = (jnp.einsum("ed,dhf->ehf", cx * (eta_l * vf)[:, None],
                              w_left)
                   + jnp.einsum("ed,dhf->ehf", cx * (eta_r * vf)[:, None],
                                w_right))
        agg = jax.ops.segment_sum(contrib, p, num_segments=n)  # (N, H, F)
        self_term = jnp.einsum("nd,dhf->nhf", nv, w_top)
        return self_term + agg

    out = jax.vmap(per_sample)(nodes, edges)
    bias = ctx.in_("Bias")
    if bias is not None:
        out = out + bias.reshape(1, 1, *bias.shape[-2:]) \
            if bias.ndim >= 2 else out + bias.reshape(1, 1, -1, 1)
    return {"Out": out}
