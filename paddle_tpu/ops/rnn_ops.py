"""Recurrent ops: LSTM / GRU cells and full sequences.

Parity: paddle/fluid/operators/lstm_op.cc, gru_op.cc, lstm_unit_op.cc,
gru_unit_op.cc, dynamic_lstm / dynamic_gru kernels.

TPU-first: the reference runs cuDNN / hand-rolled CUDA recurrences step by
step on a stream. Here the full-sequence ops are ONE ``lax.scan`` whose body
is a fused (4h or 3h wide) matmul — the time loop compiles to a single XLA
While with the gate matmuls on the MXU, and the input projection
``x @ W_x`` is hoisted OUT of the scan (one big (B*T, D)x(D, 4H) matmul)
so the sequential part touches only the (H, 4H) recurrent weight.

Ragged batches use a ``Length`` tensor: steps past a row's length carry the
previous state through unchanged (mask-select in the scan body), the static-
shape equivalent of LoD-aware kernels.
"""

import jax
import jax.numpy as jnp

from . import register


def _len_mask(lengths, t, dtype):
    # (B, 1) validity of timestep t given per-row lengths
    return (t < lengths.reshape(-1, 1)).astype(dtype)


def _lstm_scan(xw, h0, c0, w_h, bias, lengths, use_peepholes=False,
               w_peep=None, gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
               cand_act=jnp.tanh, reverse=False):
    """Core LSTM recurrence. xw: (B, T, 4H) pre-projected inputs.

    Gate order follows the fluid convention (lstm_op.h): i, f, c, o.
    """
    b, t, four_h = xw.shape
    h = four_h // 4
    if bias is not None:
        xw = xw + bias[: 4 * h]
    xs = jnp.swapaxes(xw, 0, 1)  # (T, B, 4H) — scan over leading axis
    if reverse:
        xs = xs[::-1]
    steps = jnp.arange(t)
    if reverse:
        steps = steps[::-1]

    def body(carry, inp):
        h_prev, c_prev = carry
        x_t, step = inp
        gates = x_t + h_prev @ w_h  # (B, 4H), the only sequential matmul
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes and w_peep is not None:
            wi, wf, wo = jnp.split(w_peep, 3)
            i = i + c_prev * wi
            f = f + c_prev * wf
        i, f = gate_act(i), gate_act(f)
        c_new = f * c_prev + i * cand_act(c_hat)
        if use_peepholes and w_peep is not None:
            o = o + c_new * wo
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        if lengths is not None:
            m = _len_mask(lengths, step, h_new.dtype)
            h_new = m * h_new + (1 - m) * h_prev
            c_new = m * c_new + (1 - m) * c_prev
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = jax.lax.scan(body, (h0, c0), (xs, steps))
    hs = jnp.swapaxes(hs, 0, 1)  # (B, T, H)
    cs = jnp.swapaxes(cs, 0, 1)
    if reverse:
        hs, cs = hs[:, ::-1], cs[:, ::-1]
    return hs, cs, h_last, c_last


@register("lstm")
def lstm(ctx):
    """Full-sequence (possibly ragged) LSTM.

    Inputs: Input (B, T, D), WeightX (D, 4H), WeightH (H, 4H), Bias (4H,)
    [+ peephole tail (3H,)], optional H0/C0 (B, H), optional Length (B,).
    Outputs: Hidden (B, T, H), Cell (B, T, H), LastH, LastC.
    """
    x = ctx.in_("Input")
    w_x = ctx.in_("WeightX")
    w_h = ctx.in_("WeightH")
    bias = ctx.in_("Bias")
    lengths = ctx.in_("Length")
    use_peep = bool(ctx.attr("use_peepholes", False))
    h = w_h.shape[0]
    w_peep = None
    if bias is not None and use_peep and bias.shape[0] == 7 * h:
        bias, w_peep = bias[: 4 * h], bias[4 * h:]
    b = x.shape[0]
    h0 = ctx.in_("H0")
    c0 = ctx.in_("C0")
    if h0 is None:
        h0 = jnp.zeros((b, h), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, h), x.dtype)
    xw = x @ w_x  # hoisted: one (B*T, D)x(D, 4H) MXU matmul
    hs, cs, h_last, c_last = _lstm_scan(
        xw, h0, c0, w_h, bias, lengths, use_peepholes=use_peep, w_peep=w_peep,
        reverse=bool(ctx.attr("is_reverse", False)))
    return {"Hidden": hs, "Cell": cs, "LastH": h_last, "LastC": c_last}


@register("gru")
def gru(ctx):
    """Full-sequence GRU. Gate order follows gru_op.h: update u, reset r,
    candidate c; candidate uses (r * h_prev) @ W_c like the reference.

    origin_mode selects the output blend (gru_kernel.h gru_finalOutput):
    False (the fluid DEFAULT) -> h = (1-u)*h_prev + u*c;
    True (the original GRU paper) -> h = u*h_prev + (1-u)*c.

    Inputs: Input (B,T,D), WeightX (D,3H), WeightH (H,3H), Bias (3H,),
    optional H0 (B,H), Length (B,).
    """
    x = ctx.in_("Input")
    w_x = ctx.in_("WeightX")
    w_h = ctx.in_("WeightH")
    bias = ctx.in_("Bias")
    lengths = ctx.in_("Length")
    h = w_h.shape[0]
    b = x.shape[0]
    h0 = ctx.in_("H0")
    if h0 is None:
        h0 = jnp.zeros((b, h), x.dtype)
    xw = x @ w_x
    if bias is not None:
        xw = xw + bias
    w_h_gates = w_h[:, : 2 * h]   # (H, 2H) for u, r
    w_h_cand = w_h[:, 2 * h:]     # (H, H) for candidate
    xs = jnp.swapaxes(xw, 0, 1)
    reverse = bool(ctx.attr("is_reverse", False))
    if reverse:
        xs = xs[::-1]
    steps = jnp.arange(x.shape[1])
    if reverse:
        steps = steps[::-1]

    origin_mode = bool(ctx.attr("origin_mode", False))

    def body(h_prev, inp):
        x_t, step = inp
        ur = jax.nn.sigmoid(x_t[:, : 2 * h] + h_prev @ w_h_gates)
        u, r = ur[:, :h], ur[:, h:]
        c = jnp.tanh(x_t[:, 2 * h:] + (r * h_prev) @ w_h_cand)
        h_new = (u * h_prev + (1 - u) * c) if origin_mode \
            else ((1 - u) * h_prev + u * c)
        if lengths is not None:
            m = _len_mask(lengths, step, h_new.dtype)
            h_new = m * h_new + (1 - m) * h_prev
        return h_new, h_new

    h_last, hs = jax.lax.scan(body, h0, (xs, steps))
    hs = jnp.swapaxes(hs, 0, 1)
    if reverse:
        hs = hs[:, ::-1]
    return {"Hidden": hs, "LastH": h_last}


@register("lstm_unit")
def lstm_unit(ctx):
    """Single LSTM step. Inputs: X (B, 4H) pre-projected gates (x@Wx + h@Wh
    done by the caller via fc, fluid-style), C_prev (B, H)."""
    gates = ctx.in_("X")
    c_prev = ctx.in_("C_prev")
    forget_bias = ctx.attr("forget_bias", 0.0)
    i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * jnp.tanh(c_hat)
    h = o * jnp.tanh(c)
    return {"Hidden": h, "Cell": c}


@register("gru_unit")
def gru_unit(ctx):
    """Single GRU step. Inputs: Input (B, 3H) = x@Wx (+bias), HiddenPrev
    (B, H), Weight (H, 3H)."""
    x = ctx.in_("Input")
    h_prev = ctx.in_("HiddenPrev")
    w = ctx.in_("Weight")
    bias = ctx.in_("Bias")
    if bias is not None:
        x = x + bias
    h = h_prev.shape[-1]
    ur = jax.nn.sigmoid(x[:, : 2 * h] + h_prev @ w[:, : 2 * h])
    u, r = ur[:, :h], ur[:, h:]
    c = jnp.tanh(x[:, 2 * h:] + (r * h_prev) @ w[:, 2 * h:])
    # origin_mode=False is the fluid default (gru_finalOutput)
    if bool(ctx.attr("origin_mode", False)):
        h_new = u * h_prev + (1 - u) * c
    else:
        h_new = (1 - u) * h_prev + u * c
    return {"Hidden": h_new, "Gate": jnp.concatenate([ur, c], -1),
            "ResetHiddenPrev": r * h_prev}


@register("lstmp")
def lstmp(ctx):
    """LSTM with recurrent projection (dynamic_lstmp parity,
    ref operators/lstmp_op.h). The recurrent state is the PROJECTED
    r_t = proj_act(h_t @ W_proj) (B, P); WeightH is (P, 4H).
    Outputs Projection (B, T, P) and Cell (B, T, H)."""
    x = ctx.in_("Input")
    w_x = ctx.in_("WeightX")                     # (D, 4H)
    w_h = ctx.in_("WeightH")                     # (P, 4H)
    w_proj = ctx.in_("ProjWeight")               # (H, P)
    bias = ctx.in_("Bias")
    lengths = ctx.in_("Length")
    h = w_x.shape[1] // 4
    p = w_proj.shape[1]
    use_peep = bool(ctx.attr("use_peepholes", False))
    w_peep = None
    if bias is not None and use_peep and bias.shape[0] == 7 * h:
        bias, w_peep = bias[: 4 * h], bias[4 * h:]
    b, t, _ = x.shape
    r0 = ctx.in_("H0")
    c0 = ctx.in_("C0")
    if r0 is None:
        r0 = jnp.zeros((b, p), x.dtype)
    elif r0.shape[-1] == h:                      # H0 given in cell space
        r0 = r0 @ w_proj
    if c0 is None:
        c0 = jnp.zeros((b, h), x.dtype)
    acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}
    gate_act = acts[ctx.attr("gate_activation", "sigmoid")]
    cell_act = acts[ctx.attr("cell_activation", "tanh")]
    cand_act = acts[ctx.attr("candidate_activation", "tanh")]
    proj_act = acts[ctx.attr("proj_activation", "tanh")]

    reverse = bool(ctx.attr("is_reverse", False))
    xw = x @ w_x
    if bias is not None:
        xw = xw + bias
    xs = jnp.swapaxes(xw, 0, 1)
    steps = jnp.arange(t)
    if reverse:
        xs = xs[::-1]
        steps = steps[::-1]

    def body(carry, inp):
        r_prev, c_prev = carry
        x_t, step = inp
        gates = x_t + r_prev @ w_h
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        if w_peep is not None:
            wi, wf, wo = jnp.split(w_peep, 3)
            i = i + c_prev * wi
            f = f + c_prev * wf
        i, f = gate_act(i), gate_act(f)
        c_new = f * c_prev + i * cand_act(c_hat)
        if w_peep is not None:
            o = o + c_new * wo
        o = gate_act(o)
        r_new = proj_act((o * cell_act(c_new)) @ w_proj)
        if lengths is not None:
            m = _len_mask(lengths, step, r_new.dtype)
            r_new = m * r_new + (1 - m) * r_prev
            c_new = m * c_new + (1 - m) * c_prev
        return (r_new, c_new), (r_new, c_new)

    (r_last, c_last), (rs, cs) = jax.lax.scan(body, (r0, c0), (xs, steps))
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if reverse:
        rs, cs = rs[:, ::-1], cs[:, ::-1]
    return {"Projection": rs, "Cell": cs,
            "LastH": r_last, "LastC": c_last}


_BASIC_ACTS = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
               "relu": jax.nn.relu, "identity": lambda v: v}


@register("basic_gru")
def basic_gru(ctx):
    """One direction x one layer of contrib basic_gru, full sequence.

    Parity: python/paddle/fluid/contrib/layers/rnn_impl.py:22-137 (the
    BasicGRUUnit recurrence the reference unrolls via StaticRNN); here the
    whole layer is ONE lax.scan with the input projections hoisted out onto
    a single (B*T, D)x(D, 3H) MXU matmul.

    Weight layout matches the unit: GateW (D+H, 2H) producing (r, u) in that
    split order (rnn_impl.py:125 ``r, u = split(gate_input, 2)``), CandW
    (D+H, H). The candidate uses ``(r * h_prev) @ CandW_h`` — the unit's
    DOCUMENTED math (rnn_impl.py:33 ``m_t = actNode(W_cx x + W_ch dot(r, h)
    + b)``). NOTE the reference 1.5 *code* computes r_hidden and then never
    uses it (rnn_impl.py:127-131 feeds pre_hidden, not r_hidden, to the
    candidate matmul — the reset gate is dead there, fixed in later Paddle);
    we implement the documented equations.

    Blend is u*h + (1-u)*c (rnn_impl.py:135 — the original-paper form, NOT
    the gru_op default blend).

    Inputs: Input (B,T,D), GateW (D+H,2H), GateB (2H,), CandW (D+H,H),
    CandB (H,), optional H0 (B,H), optional Length (B,).
    Outputs: Hidden (B,T,H), LastH (B,H).
    """
    x = ctx.in_("Input")
    gate_w = ctx.in_("GateW")
    gate_b = ctx.in_("GateB")
    cand_w = ctx.in_("CandW")
    cand_b = ctx.in_("CandB")
    lengths = ctx.in_("Length")
    d = x.shape[-1]
    h = cand_w.shape[-1]
    b, t = x.shape[0], x.shape[1]
    h0 = ctx.in_("H0")
    if h0 is None:
        h0 = jnp.zeros((b, h), x.dtype)
    act_g = _BASIC_ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_c = _BASIC_ACTS[ctx.attr("activation", "tanh")]
    # hoist: input half of both projections out of the scan
    xg = x @ gate_w[:d] + gate_b          # (B, T, 2H)
    xc = x @ cand_w[:d] + cand_b          # (B, T, H)
    wh_g = gate_w[d:]                     # (H, 2H)
    wh_c = cand_w[d:]                     # (H, H)
    xs_g = jnp.swapaxes(xg, 0, 1)
    xs_c = jnp.swapaxes(xc, 0, 1)
    steps = jnp.arange(t)
    reverse = bool(ctx.attr("is_reverse", False))
    if reverse:
        xs_g, xs_c, steps = xs_g[::-1], xs_c[::-1], steps[::-1]

    def body(h_prev, inp):
        g_t, c_t, step = inp
        gates = act_g(g_t + h_prev @ wh_g)
        r, u = gates[:, :h], gates[:, h:]
        c = act_c(c_t + (r * h_prev) @ wh_c)
        h_new = u * h_prev + (1 - u) * c
        if lengths is not None:
            m = _len_mask(lengths, step, h_new.dtype)
            h_new = m * h_new + (1 - m) * h_prev
        return h_new, h_new

    h_last, hs = jax.lax.scan(body, h0, (xs_g, xs_c, steps))
    hs = jnp.swapaxes(hs, 0, 1)
    if reverse:
        hs = hs[:, ::-1]
    return {"Hidden": hs, "LastH": h_last}


@register("basic_lstm")
def basic_lstm(ctx):
    """One direction x one layer of contrib basic_lstm, full sequence.

    Parity: python/paddle/fluid/contrib/layers/rnn_impl.py:622-764
    (BasicLSTMUnit: single fused Weight (D+H, 4H), gate split order
    (i, j, f, o) at rnn_impl.py:736, forget_bias added to f) unrolled by
    StaticRNN at rnn_impl.py:515-612; here one lax.scan, input projection
    hoisted.

    Inputs: Input (B,T,D), Weight (D+H,4H), Bias (4H,), optional H0/C0
    (B,H), optional Length (B,). Outputs: Hidden (B,T,H), LastH, LastC.

    Implementation: the recurrence IS the fluid lstm one, so this reuses
    _lstm_scan after a one-time block permutation of the 4H columns from
    contrib order (i, j, f, o) to fluid order (i, f, c, o) and folding
    forget_bias into the f bias slice — XLA constant-folds both.
    """
    x = ctx.in_("Input")
    w = ctx.in_("Weight")
    bias = ctx.in_("Bias")
    lengths = ctx.in_("Length")
    d = x.shape[-1]
    h = w.shape[-1] // 4
    b = x.shape[0]
    h0 = ctx.in_("H0")
    c0 = ctx.in_("C0")
    if h0 is None:
        h0 = jnp.zeros((b, h), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, h), x.dtype)
    act_g = _BASIC_ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_c = _BASIC_ACTS[ctx.attr("activation", "tanh")]
    fb = ctx.attr("forget_bias", 1.0)

    def ifco(m):                          # (..., 4H) contrib -> fluid order
        return jnp.concatenate([m[..., :h], m[..., 2 * h:3 * h],
                                m[..., h:2 * h], m[..., 3 * h:]], axis=-1)

    bias_p = ifco(bias).at[h:2 * h].add(fb)
    hs, _, h_last, c_last = _lstm_scan(
        ifco(x @ w[:d]), h0, c0, ifco(w[d:]), bias_p, lengths,
        gate_act=act_g, cell_act=act_c, cand_act=act_c,
        reverse=bool(ctx.attr("is_reverse", False)))
    return {"Hidden": hs, "LastH": h_last, "LastC": c_last}
