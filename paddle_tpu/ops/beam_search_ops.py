"""Beam-search ops.

Parity: paddle/fluid/operators/beam_search_op.cc + beam_search_decode_op.cc.
The reference implements beam search as LoD-tensor surgery inside a While
block (variable beam widths per source, pruned via LoD offsets). The
TPU-native design keeps everything static-shape: beams are a dense
(batch, beam) lane, finished beams are masked (score frozen at their final
value), and the whole decode is a single ``lax.scan``/``while_loop`` — no
host round-trips per step.
"""

import jax
import jax.numpy as jnp

from . import register, DEVICE_INT

NEG_INF = -1e9


def beam_search_step(log_probs, beam_scores, finished, beam_size, end_id,
                     length_penalty=0.0, step=None):
    """One expansion: (B, K, V) token log-probs + (B, K) running scores
    -> top-K of the K*V continuations.

    Returns (new_scores, parent_idx (B, K), token_ids (B, K), finished).
    Finished beams only propose <end> (score unchanged) so they occupy
    exactly one slot, the static-shape analogue of the reference's LoD prune.
    """
    b, k, v = log_probs.shape
    # Finished beams: freeze — only continuation is <end> with prob 1.
    frozen = jnp.full((b, k, v), NEG_INF, log_probs.dtype)
    frozen = frozen.at[:, :, end_id].set(0.0)
    log_probs = jnp.where(finished[..., None], frozen, log_probs)
    cand = beam_scores[..., None] + log_probs  # (B, K, V)
    flat = cand.reshape(b, k * v)
    new_scores, flat_idx = jax.lax.top_k(flat, k)
    parent = flat_idx // v
    tokens = flat_idx % v
    new_finished = jnp.take_along_axis(finished, parent, axis=1) | (tokens == end_id)
    return new_scores, parent, tokens, new_finished


@register("beam_search")
def beam_search_op(ctx):
    """Single-step parity op. Inputs: Scores (B*K, V) post-softmax probs
    (fluid feeds probs; we take log inside), PreScores (B*K, 1),
    PreIds (B*K, 1). Attr beam_size, end_id."""
    scores = ctx.in_("Scores")
    pre_scores = ctx.in_("PreScores")
    pre_ids = ctx.in_("PreIds")
    k = ctx.attr("beam_size")
    end_id = ctx.attr("end_id")
    v = scores.shape[-1]
    b = scores.shape[0] // k
    log_probs = jnp.log(jnp.maximum(scores, 1e-20)).reshape(b, k, v)
    beam_scores = pre_scores.reshape(b, k)
    finished = (pre_ids.reshape(b, k) == end_id)
    new_scores, parent, tokens, _ = beam_search_step(
        log_probs, beam_scores, finished, k, end_id)
    batch_offset = (jnp.arange(b) * k)[:, None]
    return {"SelectedIds": tokens.reshape(b * k, 1).astype(DEVICE_INT),
            "SelectedScores": new_scores.reshape(b * k, 1),
            "ParentIdx": (parent + batch_offset).reshape(b * k).astype(jnp.int32)}


@register("beam_search_decode")
def beam_search_decode_op(ctx):
    """Backtrack stacked (T, B, K) ids/parents into final sequences.

    Inputs: Ids (T, B, K) int tokens, ParentIdx (T, B, K), Scores (B, K).
    Outputs: SentenceIds (B, K, T) backtracked, SentenceScores (B, K).
    """
    ids = ctx.in_("Ids")
    parents = ctx.in_("ParentIdx")
    scores = ctx.in_("Scores")
    t, b, k = ids.shape

    # Parents may arrive in either convention: per-batch beam index [0, K)
    # or flattened (batch*K + beam) as the beam_search op emits for
    # flat-tensor gathers. Reduce mod K to per-batch.
    parents = parents % k

    def back(carry, inp):
        beam_ptr = carry  # (B, K) which beam each final lane follows
        ids_t, par_t = inp
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)
        beam_ptr = jnp.take_along_axis(par_t, beam_ptr, axis=1)
        return beam_ptr, tok

    init = jnp.tile(jnp.arange(k)[None], (b, 1))
    _, toks = jax.lax.scan(back, init, (ids[::-1], parents[::-1]))
    seqs = jnp.transpose(toks[::-1], (1, 2, 0))  # (B, K, T)
    return {"SentenceIds": seqs, "SentenceScores": scores}
