"""Optimizer update ops.

Parity: paddle/fluid/operators/optimizers/*.cc (sgd_op, momentum_op, adam_op,
lamb_op, ...). Each op consumes Param, Grad (the `param@GRAD` env entry the
backward pass produced via jax.grad) and accumulator state, and writes the
new values back under the *same* variable names — the functional equivalent
of fluid's in-place device-side updates. Because these run inside the same
jitted step as forward+backward, XLA fuses the whole optimizer into a couple
of elementwise kernels over each parameter — no kernel-per-param launches.
"""

import jax.numpy as jnp
from jax import lax

from . import register


def _lr(ctx):
    lr = ctx.in_("LearningRate")
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register("sgd")
def sgd(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    return {"ParamOut": (p - _lr(ctx) * g).astype(p.dtype)}


@register("momentum")
def momentum(ctx):
    p, g, v = ctx.in_("Param"), ctx.in_("Grad"), ctx.in_("Velocity")
    mu = ctx.attr("mu")
    lr = _lr(ctx)
    v_new = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new.astype(p.dtype), "VelocityOut": v_new}


@register("lars_momentum")
def lars_momentum(ctx):
    p, g, v = ctx.in_("Param"), ctx.in_("Grad"), ctx.in_("Velocity")
    mu = ctx.attr("mu")
    lars_coeff = ctx.attr("lars_coeff", 0.001)
    lars_wd = ctx.attr("lars_weight_decay", 0.0005)
    lr = _lr(ctx)
    pn = jnp.sqrt(jnp.sum(p * p))
    gn = jnp.sqrt(jnp.sum(g * g))
    local_lr = lr * lars_coeff * pn / (gn + lars_wd * pn + 1e-12)
    v_new = mu * v + local_lr * (g + lars_wd * p)
    return {"ParamOut": (p - v_new).astype(p.dtype), "VelocityOut": v_new}


@register("adam")
def adam(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    m, v = ctx.in_("Moment1"), ctx.in_("Moment2")
    b1p = ctx.in_("Beta1Pow")
    b2p = ctx.in_("Beta2Pow")
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    g = g.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m_new,
            "Moment2Out": v_new, "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register("adamw")
def adamw(ctx):
    p = ctx.in_("Param")
    wd = ctx.attr("weight_decay", 0.01)
    lr = _lr(ctx)
    outs = adam(ctx)
    outs["ParamOut"] = (outs["ParamOut"] - lr * wd * p).astype(p.dtype)
    return outs


@register("adamax")
def adamax(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    m, inf = ctx.in_("Moment"), ctx.in_("InfNorm")
    b1p = ctx.in_("Beta1Pow")
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    p_new = p - (lr / (1 - b1p.reshape(()))) * (m_new / (inf_new + eps))
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": m_new,
            "InfNormOut": inf_new, "Beta1PowOut": b1p * b1}


@register("adagrad")
def adagrad(ctx):
    p, g, mom = ctx.in_("Param"), ctx.in_("Grad"), ctx.in_("Moment")
    eps = ctx.attr("epsilon", 1e-6)
    mom_new = mom + g * g
    p_new = p - _lr(ctx) * g / (jnp.sqrt(mom_new) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": mom_new}


@register("decayed_adagrad")
def decayed_adagrad(ctx):
    p, g, mom = ctx.in_("Param"), ctx.in_("Grad"), ctx.in_("Moment")
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    mom_new = decay * mom + (1 - decay) * g * g
    p_new = p - _lr(ctx) * g / (jnp.sqrt(mom_new) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": mom_new}


@register("adadelta")
def adadelta(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    avg_sq_g, avg_sq_u = ctx.in_("AvgSquaredGrad"), ctx.in_("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    avg_sq_g_new = rho * avg_sq_g + (1 - rho) * g * g
    upd = -jnp.sqrt((avg_sq_u + eps) / (avg_sq_g_new + eps)) * g
    avg_sq_u_new = rho * avg_sq_u + (1 - rho) * upd * upd
    return {"ParamOut": (p + upd).astype(p.dtype),
            "AvgSquaredGradOut": avg_sq_g_new, "AvgSquaredUpdateOut": avg_sq_u_new}


@register("rmsprop")
def rmsprop(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    ms, mom = ctx.in_("MeanSquare"), ctx.in_("Moment")
    rho = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    momentum_ = ctx.attr("momentum", 0.0)
    lr = _lr(ctx)
    ms_new = rho * ms + (1 - rho) * g * g
    if ctx.attr("centered", False):
        mg = ctx.in_("MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        mom_new = momentum_ * mom + lr * g / jnp.sqrt(ms_new - mg_new * mg_new + eps)
        return {"ParamOut": (p - mom_new).astype(p.dtype), "MeanSquareOut": ms_new,
                "MomentOut": mom_new, "MeanGradOut": mg_new}
    mom_new = momentum_ * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": (p - mom_new).astype(p.dtype), "MeanSquareOut": ms_new,
            "MomentOut": mom_new}


@register("ftrl")
def ftrl(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    sq, lin = ctx.in_("SquaredAccumulator"), ctx.in_("LinearAccumulator")
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + g * g
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    p_new = pre / denom
    return {"ParamOut": p_new.astype(p.dtype), "SquaredAccumOut": new_sq,
            "LinearAccumOut": new_lin}


@register("lamb")
def lamb(ctx):
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    m, v = ctx.in_("Moment1"), ctx.in_("Moment2")
    b1p, b2p = ctx.in_("Beta1Pow"), ctx.in_("Beta2Pow")
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-6)
    wd = ctx.attr("weight_decay", 0.01)
    lr = _lr(ctx)
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    m_hat = m_new / (1 - b1p.reshape(()))
    v_hat = v_new / (1 - b2p.reshape(()))
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * pf
    p_norm = jnp.sqrt(jnp.sum(pf * pf))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_new = pf - lr * trust * r
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m_new,
            "Moment2Out": v_new, "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register("dpsgd")
def dpsgd(ctx):
    # Differentially-private SGD (clip + noise); noise keyed per-op.
    import jax
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    clip = ctx.attr("clip", 10.0)
    sigma = ctx.attr("sigma", 1.0)
    gn = jnp.sqrt(jnp.sum(g * g))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(gn, 1e-12))
    g = g + sigma * jax.random.normal(ctx.rng(), g.shape, g.dtype)
    return {"ParamOut": (p - _lr(ctx) * g).astype(p.dtype)}


@register("proximal_gd")
def proximal_gd(ctx):
    """Parity: proximal_gd_op: prox step on z = p - lr*g:
    p' = sign(z) * max(|z| - lr*l1, 0) / (1 + lr*l2)."""
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    lr = _lr(ctx)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    z = p - lr * g
    p_new = jnp.sign(z) * jnp.maximum(jnp.abs(z) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": p_new.astype(p.dtype)}


@register("proximal_adagrad")
def proximal_adagrad(ctx):
    """Parity: proximal_adagrad_op: adagrad-scaled lr, then the same
    soft-threshold prox as proximal_gd."""
    p, g = ctx.in_("Param"), ctx.in_("Grad")
    m = ctx.in_("Moment")
    lr = _lr(ctx)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_new = m + g * g
    eff = lr / jnp.sqrt(m_new + 1e-10)
    z = p - eff * g
    p_new = jnp.sign(z) * jnp.maximum(jnp.abs(z) - eff * l1, 0.0) \
        / (1.0 + eff * l2)
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": m_new}


@register("decoupled_weight_decay")
def decoupled_weight_decay(ctx):
    """Parity: contrib extend_with_decoupled_weight_decay (AdamW-style):
    after the base optimizer update, ParamOut = Param - coeff * the
    PRE-update parameter snapshot (Loshchilov & Hutter 2017)."""
    p, pre = ctx.in_("Param"), ctx.in_("PrevParam")
    coeff = ctx.attr("coeff", 0.0)
    return {"ParamOut": (p - coeff * pre).astype(p.dtype)}
